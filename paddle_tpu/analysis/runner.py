"""Drive all analyzers over a file set.

File discovery skips ``__pycache__``, hidden directories, and
``lint_fixtures`` (deliberately-bad snippets used to test the linter
itself).  ``paddle_tpu/flags.py`` is always consulted for flag
definitions — pre-parsed when it is outside the analyzed paths, or
ordered first when inside them — so ``flag-undefined`` sees the full
registry no matter which subset of the repo is linted.
"""
from __future__ import annotations

import ast
import os

from . import clocks, flags_metrics, jit_safety, lock_discipline
from .core import Finding, SourceFile

__all__ = ["ALL_RULES", "run", "iter_files"]

ALL_RULES: dict[str, str] = {}
ALL_RULES.update(jit_safety.RULES)
ALL_RULES.update(lock_discipline.RULES)
ALL_RULES.update(flags_metrics.RULES)
ALL_RULES.update(clocks.RULES)
ALL_RULES["parse-error"] = "file failed to parse"

_SKIP_DIRS = {"__pycache__", "lint_fixtures", ".git"}

_FLAGS_REL = "paddle_tpu/flags.py"


def iter_files(paths, root):
    """(abspath, repo-relative posix path) pairs, deterministic order,
    flags.py first so its definitions precede every read site."""
    out = []
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        ap = os.path.normpath(ap)
        if os.path.isfile(ap):
            _add(out, seen, ap, root)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        _add(out, seen, os.path.join(dirpath, fn), root)
    out.sort(key=lambda pair: (pair[1] != _FLAGS_REL, pair[1]))
    return out


def _add(out, seen, abspath, root):
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    if rel not in seen:
        seen.add(rel)
        out.append((abspath, rel))


def run(paths, root=None, rules=None) -> list[Finding]:
    """All findings (suppressions already applied) for the given paths,
    optionally restricted to a rule-id subset."""
    root = os.path.abspath(root or os.getcwd())
    files = iter_files(paths, root)

    flag_defs = {}
    if not any(rel == _FLAGS_REL for _, rel in files):
        flags_abs = os.path.join(root, _FLAGS_REL)
        if os.path.exists(flags_abs):
            try:
                fsrc = SourceFile.load(flags_abs, _FLAGS_REL)
            except SyntaxError:
                fsrc = None
            if fsrc is not None:
                for name, has_help, line in \
                        flags_metrics.collect_flag_defs(fsrc):
                    flag_defs.setdefault(
                        name, (has_help, f"{_FLAGS_REL}:{line}"))
    fm = flags_metrics.FlagsMetricsAnalyzer(flag_defs)

    findings: list[Finding] = []
    for abspath, rel in files:
        try:
            src = SourceFile.load(abspath, rel)
        except SyntaxError as e:
            findings.append(Finding(
                "parse-error", rel, e.lineno or 1,
                f"syntax error: {e.msg}",
                hint="fix the syntax error"))
            continue
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "parse-error", rel, 1, f"unreadable: {e}",
                hint="fix file encoding/permissions"))
            continue
        findings.extend(jit_safety.analyze(src))
        findings.extend(lock_discipline.analyze(src))
        findings.extend(fm.check(src))
        findings.extend(clocks.analyze(src))

    if rules is not None:
        wanted = set(rules)
        unknown = wanted - set(ALL_RULES)
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
        findings = [f for f in findings if f.rule in wanted]
    return findings
