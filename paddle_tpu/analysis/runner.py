"""Drive all analyzers over a file set.

File discovery skips ``__pycache__``, hidden directories, and
``lint_fixtures`` (deliberately-bad snippets used to test the linter
itself).  ``paddle_tpu/flags.py`` is always consulted for flag
definitions — pre-parsed when it is outside the analyzed paths, or
ordered first when inside them — so ``flag-undefined`` sees the full
registry no matter which subset of the repo is linted.

Per-file result cache (``.lint_cache/`` under the lint root): each
file's findings are keyed on its content hash, the analyzer sources'
hash, and a rolling hash of the cross-file analyzer state (the
flag/metric registries accumulated by the files before it) — so a warm
repo-wide run skips parsing entirely, while editing any file, any
analyzer, or anything that shifts an earlier file's flag/metric
contributions recomputes exactly what that change can affect.  Cached
findings are per-file and unfiltered, so the ``rules`` subset never
needs to be part of the key.
"""
from __future__ import annotations

import ast
import hashlib
import json
import os

from . import clocks, dtype_flow, effects, flags_metrics, interlock, \
    jit_safety, lock_discipline, shard_safety
from .core import Finding, SourceFile, _suppression_map

__all__ = ["ALL_RULES", "run", "iter_files"]

ALL_RULES: dict[str, str] = {}
ALL_RULES.update(jit_safety.RULES)
ALL_RULES.update(lock_discipline.RULES)
ALL_RULES.update(interlock.RULES)
ALL_RULES.update(flags_metrics.RULES)
ALL_RULES.update(clocks.RULES)
ALL_RULES.update(effects.RULES)
ALL_RULES.update(dtype_flow.RULES)
ALL_RULES.update(shard_safety.RULES)
ALL_RULES["parse-error"] = "file failed to parse"

_SKIP_DIRS = {"__pycache__", "lint_fixtures", ".git", ".lint_cache"}

_FLAGS_REL = "paddle_tpu/flags.py"

_FINDING_FIELDS = ("rule", "path", "line", "message", "severity", "hint")


def iter_files(paths, root):
    """(abspath, repo-relative posix path) pairs, deterministic order,
    flags.py first so its definitions precede every read site."""
    out = []
    seen = set()
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        ap = os.path.normpath(ap)
        if os.path.isfile(ap):
            _add(out, seen, ap, root)
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d not in _SKIP_DIRS and not d.startswith("."))
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        _add(out, seen, os.path.join(dirpath, fn), root)
    out.sort(key=lambda pair: (pair[1] != _FLAGS_REL, pair[1]))
    return out


def _add(out, seen, abspath, root):
    rel = os.path.relpath(abspath, root).replace(os.sep, "/")
    if rel not in seen:
        seen.add(rel)
        out.append((abspath, rel))


# ----------------------------------------------------------------- cache
_ANALYSIS_DIR = os.path.dirname(os.path.abspath(__file__))
_version_cache: str | None = None


def _analyzers_version() -> str:
    """Hash of the analyzer sources themselves — editing any analyzer
    invalidates every cached result."""
    global _version_cache
    if _version_cache is None:
        h = hashlib.sha1()
        for fn in sorted(os.listdir(_ANALYSIS_DIR)):
            if fn.endswith(".py"):
                h.update(fn.encode())
                with open(os.path.join(_ANALYSIS_DIR, fn), "rb") as f:
                    h.update(f.read())
        _version_cache = h.hexdigest()
    return _version_cache


class _Cache:
    """One JSON file per linted source file; best-effort (any I/O or
    decode problem silently degrades to a recompute)."""

    def __init__(self, dir_):
        self.dir = dir_
        try:
            os.makedirs(dir_, exist_ok=True)
            self.ok = True
        except OSError:
            self.ok = False

    def _path(self, rel):
        name = hashlib.sha1(rel.encode()).hexdigest()[:24]
        return os.path.join(self.dir, name + ".json")

    def get(self, rel, key):
        if not self.ok:
            return None
        try:
            with open(self._path(rel), encoding="utf-8") as f:
                ent = json.load(f)
        except (OSError, ValueError):
            return None
        return ent if ent.get("key") == key else None

    def put(self, rel, key, findings, flags, metrics, contrib):
        if not self.ok:
            return
        ent = {"key": key, "rel": rel,
               "findings": [{k: getattr(f, k) for k in _FINDING_FIELDS}
                            for f in findings],
               "flags": flags, "metrics": metrics, "contrib": contrib}
        path = self._path(rel)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(ent, f)
            os.replace(tmp, path)
        except OSError:
            pass


def run(paths, root=None, rules=None, cache=True) -> list[Finding]:
    """All findings (suppressions already applied) for the given paths,
    optionally restricted to a rule-id subset."""
    root = os.path.abspath(root or os.getcwd())
    files = iter_files(paths, root)

    flag_defs = {}
    if not any(rel == _FLAGS_REL for _, rel in files):
        flags_abs = os.path.join(root, _FLAGS_REL)
        if os.path.exists(flags_abs):
            try:
                fsrc = SourceFile.load(flags_abs, _FLAGS_REL)
            except SyntaxError:
                fsrc = None
            if fsrc is not None:
                for name, has_help, line in \
                        flags_metrics.collect_flag_defs(fsrc):
                    flag_defs.setdefault(
                        name, (has_help, f"{_FLAGS_REL}:{line}"))
    fm = flags_metrics.FlagsMetricsAnalyzer(flag_defs)

    cache_obj = _Cache(os.path.join(root, ".lint_cache")) if cache \
        else None
    # rolling hash of the cross-file analyzer state: seeded with the
    # analyzer version + pre-parsed flag defs, advanced per file by its
    # flag/metric contributions (cached or fresh)
    state = hashlib.sha1(_analyzers_version().encode()) if cache_obj \
        else None
    if state is not None:
        state.update(repr(sorted(flag_defs.items())).encode())

    findings: list[Finding] = []
    for abspath, rel in files:
        try:
            with open(abspath, encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(
                "parse-error", rel, 1, f"unreadable: {e}",
                hint="fix file encoding/permissions"))
            continue

        key = None
        if cache_obj is not None:
            key = hashlib.sha1(
                "\x00".join((rel,
                             hashlib.sha1(text.encode()).hexdigest(),
                             state.hexdigest())).encode()).hexdigest()
            ent = cache_obj.get(rel, key)
            if ent is not None:
                findings.extend(
                    Finding(**{k: d[k] for k in _FINDING_FIELDS})
                    for d in ent["findings"])
                for name, v in ent["flags"].items():
                    fm.flags.setdefault(name, tuple(v))
                for name, v in ent["metrics"].items():
                    fm.metrics.setdefault(name, tuple(v))
                state.update(ent["contrib"].encode())
                continue

        try:
            tree = ast.parse(text, filename=rel)
        except SyntaxError as e:
            pe = Finding(
                "parse-error", rel, e.lineno or 1,
                f"syntax error: {e.msg}",
                hint="fix the syntax error")
            findings.append(pe)
            if cache_obj is not None:
                cache_obj.put(rel, key, [pe], {}, {}, "")
            continue
        src = SourceFile(rel, text, tree, _suppression_map(text))

        before_flags = set(fm.flags)
        before_metrics = set(fm.metrics)
        file_findings: list[Finding] = []
        file_findings.extend(jit_safety.analyze(src))
        file_findings.extend(lock_discipline.analyze(src))
        file_findings.extend(interlock.analyze(src))
        file_findings.extend(fm.check(src))
        file_findings.extend(clocks.analyze(src))
        file_findings.extend(effects.analyze(src))
        file_findings.extend(dtype_flow.analyze(src))
        file_findings.extend(shard_safety.analyze(src))
        findings.extend(file_findings)

        if cache_obj is not None:
            new_flags = {k: list(fm.flags[k]) for k in fm.flags
                         if k not in before_flags}
            new_metrics = {k: list(fm.metrics[k]) for k in fm.metrics
                           if k not in before_metrics}
            contrib = repr((sorted(new_flags.items()),
                            sorted(new_metrics.items())))
            cache_obj.put(rel, key, file_findings, new_flags,
                          new_metrics, contrib)
            state.update(contrib.encode())

    if rules is not None:
        wanted = set(rules)
        unknown = wanted - set(ALL_RULES)
        if unknown:
            raise ValueError(f"unknown rule ids: {sorted(unknown)}")
        findings = [f for f in findings if f.rule in wanted]
    return findings
