"""Interprocedural lock analysis + thread-lifecycle rules.

:mod:`.lock_discipline` is deliberately intraprocedural: it sees a lock
held across statements of one method but not across a method call.  The
serving stack's real locking, however, is layered — a public method
takes ``self.lock`` and delegates to private helpers — so this pass
re-analyzes same-class callees with the caller's held-lock set
propagated in (call depth <= 2, mirroring jit_safety's helper
analysis), and reports only the *delta* the intraprocedural pass cannot
see, under the same rule ids:

``lock-order-cycle``     an edge recorded inside a callee while the
                         caller holds another lock closes ABBA rings no
                         single method body shows;
``lock-unlocked-write``  a helper's writes count as locked when its
                         call site holds the class lock — and race with
                         call paths that do not;
``lock-blocking-call``   a sleep/join/network call in a callee blocks
                         whatever lock the caller is holding.

Three new rules ride on the same module scan:

``thread-unjoined``      a ``threading.Thread`` that is started but
                         whose handle is never joined anywhere in the
                         module (or is discarded at the start site):
                         no shutdown path can wait for it;
``thread-bare-except``   a thread target swallowing exceptions silently
                         (``except Exception: pass``) — the thread
                         stays "alive" while its work is dead;
``callback-under-lock``  a stored user callback (``on_token``-style
                         attribute) invoked while holding a lock: user
                         code that re-enters the subsystem deadlocks on
                         the very lock it was called under.

Private helpers that have same-class callers are analyzed only through
those callers (a lone entry-point traversal would misclassify their
writes as unlocked); public and uncalled-private methods are entry
points.  Call chains rooted at ``__init__`` never record writes — the
object is not shared yet.
"""
from __future__ import annotations

import ast
import re

from . import lock_discipline as _ld
from .core import Finding, SourceFile, call_name, dotted_name, expr_text

__all__ = ["analyze"]

RULES = {
    "thread-unjoined": "thread started but never joined on any "
                       "shutdown path",
    "thread-bare-except": "thread target swallows exceptions silently",
    "callback-under-lock": "stored user callback invoked while holding "
                           "a lock",
}

_MAX_DEPTH = 2          # caller -> callee -> callee's callee, then stop

_CALLBACK_RE = re.compile(
    r"^_?(on_[a-z0-9_]+|[a-z0-9_]*_(callback|cb|hook))$")


def analyze(src: SourceFile) -> list[Finding]:
    has_locks = any(ctor + "(" in src.text
                    for ctor in _ld._LOCK_CTORS | _ld._EVENT_CTORS
                    | set(_ld._FACTORY_CTORS))
    has_threads = "Thread(" in src.text
    if not (has_locks or has_threads):
        return []
    findings: list[Finding] = []
    if has_threads:
        findings.extend(_thread_rules(src))
    if has_locks:
        findings.extend(_interprocedural(src))
    return src.filter(_dedupe(findings))


def _dedupe(findings):
    seen, out = set(), []
    for f in findings:
        key = (f.fingerprint, f.line)
        if key not in seen:
            seen.add(key)
            out.append(f)
    return out


# =================================================== interprocedural pass
def _interprocedural(src: SourceFile) -> list[Finding]:
    locks = _ld._ModuleLocks(src.tree)
    pairs = list(_ld._methods(src.tree))

    # same-class method index + which methods are called via self.m()
    methods: dict[str, dict] = {}
    for cls, fn in pairs:
        if cls is not None:
            methods.setdefault(cls.name, {}).setdefault(fn.name, fn)
    called: dict[str, set] = {}
    for cls, fn in pairs:
        if cls is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                name = call_name(node) or ""
                if name.startswith("self.") and "." not in name[5:]:
                    called.setdefault(cls.name, set()).add(name[5:])

    # --- intraprocedural baseline, for the delta ---
    base_edges: dict[tuple, tuple] = {}
    base_writes: dict[tuple, dict] = {}
    base_findings: list[Finding] = []
    for cls, fn in pairs:
        v = _ld._ScopeVisitor(src, locks, cls.name if cls else None, fn,
                              base_edges, base_writes, base_findings)
        v.visit_block(fn.body, [])
    base_cycle_fps = {f.fingerprint
                      for f in _ld._cycle_findings(src, base_edges)}
    base_racy = {pair for pair, rec in base_writes.items()
                 if rec["locked"] and rec["unlocked"]}
    base_lines = {(f.rule, f.line) for f in base_findings}

    # --- interprocedural traversal: entries with propagation ---
    # seed the edge map with the intraprocedural edges so shared edges
    # keep their sites (and cycle messages/fingerprints line up)
    edges = dict(base_edges)
    writes: dict[tuple, dict] = {}
    extra: list[Finding] = []
    visited: set = set()
    seen_callbacks: set = set()
    for cls, fn in pairs:
        clsname = cls.name if cls else None
        if clsname and fn.name.startswith("_") and \
                not fn.name.startswith("__") and \
                fn.name in called.get(clsname, set()):
            continue            # helper: analyzed through its callers
        v = _InterVisitor(src, locks, clsname, fn, edges, writes, extra,
                          methods, visited, base_lines, seen_callbacks,
                          init_chain=(fn.name == "__init__"))
        v.visit_block(fn.body, [])

    out: list[Finding] = []
    for f in _ld._cycle_findings(src, edges):
        if f.fingerprint not in base_cycle_fps:
            out.append(f)
    out.extend(_inter_write_findings(src, writes, base_racy))
    out.extend(extra)
    return out


class _InterVisitor(_ld._ScopeVisitor):
    """_ScopeVisitor that descends into ``self.m(...)`` callees carrying
    the current held-lock set, and checks callback-under-lock."""

    def __init__(self, src, locks, cls, fn, edges, writes, findings,
                 methods, visited, base_lines, seen_callbacks,
                 chain=(), inherited=frozenset(), init_chain=False):
        super().__init__(src, locks, cls, fn, edges, writes, findings)
        self.methods = methods
        self.visited = visited
        self.base_lines = base_lines
        self.seen_callbacks = seen_callbacks
        self.chain = chain              # ("Cls.caller", ...) call path
        self.inherited = inherited      # lock keys held at method entry
        self.init_chain = init_chain

    def _record_writes(self, stmt, held):
        if self.init_chain:
            return                      # object not shared during init
        super()._record_writes(stmt, held)

    def _check_call(self, call, held):
        if held:
            self._check_callback(call, held)
            super()._check_call(call, held)
        self._descend(call, held)

    def _blocking(self, call, what, why, held_keys):
        if not self.chain:
            return                      # intra pass reports these
        if ("lock-blocking-call", call.lineno) in self.base_lines:
            return                      # callee's own lock: intra saw it
        if not (self.inherited & set(held_keys)):
            return
        via = " -> ".join(self.chain + (f"{self.cls}.{self.fn.name}",))
        self.findings.append(Finding(
            "lock-blocking-call", self.src.path, call.lineno,
            f"{what} while holding "
            f"{', '.join(sorted(set(held_keys)))} (held across the "
            f"call chain {via}): {why}",
            hint="move the blocking call outside the lock scope, or "
                 "release in the caller before delegating"))

    def _check_callback(self, call, held):
        func = call.func
        if not isinstance(func, ast.Attribute) or \
                not _CALLBACK_RE.match(func.attr):
            return
        recv = func.value
        if isinstance(recv, ast.Name) and recv.id == "self" and \
                func.attr in self.methods.get(self.cls or "", {}):
            return                      # a real method, not a stored cb
        key = (call.lineno, func.attr)
        if key in self.seen_callbacks:
            return
        self.seen_callbacks.add(key)
        held_keys = sorted({k for k, _ in held})
        self.findings.append(Finding(
            "callback-under-lock", self.src.path, call.lineno,
            f"user callback `{expr_text(func)}` invoked while holding "
            f"{', '.join(held_keys)} — callback code that re-enters "
            "this subsystem deadlocks on that lock",
            hint="snapshot the callback and its arguments under the "
                 "lock, release, then invoke"))

    def _descend(self, call, held):
        if len(self.chain) >= _MAX_DEPTH or self.cls is None:
            return
        func = call.func
        if not (isinstance(func, ast.Attribute) and
                isinstance(func.value, ast.Name) and
                func.value.id == "self"):
            return
        target = self.methods.get(self.cls, {}).get(func.attr)
        if target is None or target is self.fn:
            return
        key = (id(target), frozenset(k for k, _ in held))
        if key in self.visited:
            return
        self.visited.add(key)
        sub = _InterVisitor(
            self.src, self.locks, self.cls, target, self.edges,
            self.writes, self.findings, self.methods, self.visited,
            self.base_lines, self.seen_callbacks,
            chain=self.chain + (f"{self.cls}.{self.fn.name}",),
            inherited=frozenset(k for k, _ in held),
            init_chain=self.init_chain)
        sub.visit_block(target.body, list(held))


def _inter_write_findings(src, writes, base_racy) -> list[Finding]:
    out = []
    for (cls, attr), rec in sorted(writes.items()):
        if (cls, attr) in base_racy:
            continue                    # intra pass already reports it
        if not rec["locked"] or not rec["unlocked"]:
            continue
        l_path, l_line = rec["locked"][0]
        for path, line in rec["unlocked"]:
            if (path, line) == (l_path, l_line):
                where = ("reached both with and without the lock "
                         "through different callers")
            else:
                where = (f"written under the lock at {l_path}:{l_line} "
                         "(lock taken by a calling method)")
            out.append(Finding(
                "lock-unlocked-write", path, line,
                f"`self.{attr}` of {cls} is written here without the "
                f"lock, but {where} — racy if both paths run "
                "concurrently",
                hint=f"take the {cls} lock on every path that reaches "
                     "this write, or document single-threaded "
                     "ownership with a suppression"))
    return out


# ===================================================== thread lifecycle
def _thread_rules(src: SourceFile) -> list[Finding]:
    findings: list[Finding] = []
    pairs = list(_ld._methods(src.tree))
    methods: dict[str, dict] = {}
    for cls, fn in pairs:
        if cls is not None:
            methods.setdefault(cls.name, {}).setdefault(fn.name, fn)
    module_fns = {fn.name: fn for cls, fn in pairs if cls is None}

    joined = _joined_names(src.tree)
    started_attrs = _started_attrs(src.tree)

    targets: list = []          # FunctionDef bodies that run on a thread
    target_ids: set = set()

    for cls, fn in pairs:
        clsname = cls.name if cls else None
        for stmt in ast.walk(fn):
            # inline fire-and-forget: Thread(...).start()
            if isinstance(stmt, ast.Expr) and \
                    isinstance(stmt.value, ast.Call) and \
                    isinstance(stmt.value.func, ast.Attribute) and \
                    stmt.value.func.attr == "start" and \
                    isinstance(stmt.value.func.value, ast.Call) and \
                    _is_thread_ctor(stmt.value.func.value):
                findings.append(Finding(
                    "thread-unjoined", src.path, stmt.lineno,
                    "thread is started inline and its handle "
                    "discarded — it can never be joined, so no "
                    "shutdown path can wait for it",
                    hint="bind the Thread to an attribute and join it "
                         "on the shutdown path"))
                _note_target(stmt.value.func.value, clsname, fn, methods,
                             module_fns, targets, target_ids)
                continue
            if not isinstance(stmt, ast.Assign) or \
                    not isinstance(stmt.value, ast.Call) or \
                    not _is_thread_ctor(stmt.value):
                continue
            _note_target(stmt.value, clsname, fn, methods, module_fns,
                         targets, target_ids)
            for tgt in stmt.targets:
                text = expr_text(tgt)
                if text.startswith("self."):
                    attr = text.split(".", 1)[1]
                    if attr in started_attrs and attr not in joined:
                        findings.append(Finding(
                            "thread-unjoined", src.path, stmt.lineno,
                            f"thread bound to `self.{attr}` is started "
                            "but never joined anywhere in this module",
                            hint="join the handle on the shutdown "
                                 "path (stop()/close())"))
                elif isinstance(tgt, ast.Name):
                    f = _local_thread_finding(src, fn, tgt.id,
                                              stmt.lineno)
                    if f is not None:
                        findings.append(f)

    # run() methods of Thread subclasses also execute on a thread
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and any(
                (dotted_name(b) or "").rsplit(".", 1)[-1] == "Thread"
                for b in node.bases):
            run = methods.get(node.name, {}).get("run")
            if run is not None and id(run) not in target_ids:
                target_ids.add(id(run))
                targets.append(run)

    for fn in targets:
        findings.extend(_bare_except_findings(src, fn))
    return findings


def _is_thread_ctor(call) -> bool:
    name = call_name(call) or ""
    return name.rsplit(".", 1)[-1] == "Thread"


def _note_target(call, clsname, fn, methods, module_fns, targets,
                 target_ids):
    """Resolve the thread target function, when statically visible."""
    expr = None
    for kw in call.keywords:
        if kw.arg == "target":
            expr = kw.value
    if expr is None and call.args:
        expr = call.args[0]
    if expr is None:
        return
    resolved = None
    text = expr_text(expr)
    if text.startswith("self.") and clsname:
        resolved = methods.get(clsname, {}).get(text[5:])
    elif isinstance(expr, ast.Name):
        for sub in ast.walk(fn):        # nested def in the same function
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sub.name == expr.id:
                resolved = sub
                break
        if resolved is None:
            resolved = module_fns.get(expr.id)
    if resolved is not None and id(resolved) not in target_ids:
        target_ids.add(id(resolved))
        targets.append(resolved)


def _joined_names(tree) -> set:
    """Attribute names (last segment) that receive a ``.join()`` call
    anywhere in the module, with one level of local-alias resolution
    (``t = self._thread; t.join()`` marks ``_thread``)."""
    joined: set = set()
    aliases: dict[str, str] = {}        # local name -> aliased attr
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Attribute) and \
                len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            aliases[node.targets[0].id] = node.value.attr
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            recv = node.func.value
            if isinstance(recv, ast.Attribute):
                joined.add(recv.attr)
            elif isinstance(recv, ast.Name):
                joined.add(recv.id)
                if recv.id in aliases:
                    joined.add(aliases[recv.id])
    return joined


def _started_attrs(tree) -> set:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "start" and \
                isinstance(node.func.value, ast.Attribute):
            out.add(node.func.value.attr)
    return out


def _local_thread_finding(src, fn, name, lineno):
    started = joined = escaped = False
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == name:
            if node.func.attr == "start":
                started = True
            elif node.func.attr == "join":
                joined = True
        elif isinstance(node, ast.Call):
            if any(isinstance(a, ast.Name) and a.id == name
                   for a in node.args):
                escaped = True          # handed off; managed elsewhere
        elif isinstance(node, ast.Return) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == name:
            escaped = True
        elif isinstance(node, ast.Assign) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == name:
            escaped = True              # stored; attr rules take over
    if started and not joined and not escaped:
        return Finding(
            "thread-unjoined", src.path, lineno,
            f"thread `{name}` is started in {fn.name}() but never "
            "joined there (and its handle does not escape)",
            hint="join it before returning, or retain the handle for "
                 "a shutdown path")
    return None


def _own_body_nodes(fn):
    """fn's statements, not descending into nested defs — a nested def
    is analyzed as its own thread target when something runs it."""
    todo = list(fn.body)
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef,
                                      ast.ClassDef)):
                todo.append(child)


def _bare_except_findings(src, fn) -> list[Finding]:
    out = []
    for node in _own_body_nodes(fn):
        if not isinstance(node, ast.Try):
            continue
        for handler in node.handlers:
            if _is_broad(handler) and _is_silent(handler):
                out.append(Finding(
                    "thread-bare-except", src.path, handler.lineno,
                    f"thread target {fn.name}() swallows exceptions "
                    "silently — the thread keeps running (or dies) "
                    "with no trace of what went wrong",
                    hint="log the exception (traceback.print_exc() / "
                         "logger) or re-raise; silence kills "
                         "liveness debugging"))
    return out


def _is_broad(handler) -> bool:
    t = handler.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return any((dotted_name(e) or "").rsplit(".", 1)[-1] in
               ("Exception", "BaseException") for e in elts)


def _is_silent(handler) -> bool:
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant):
            continue
        return False
    return True
