"""Repo-native static analysis (pure AST, stdlib only).

Three analyzer families guard the invariants PRs 1–5 made load-bearing:

* :mod:`.jit_safety` — host syncs / python branches inside jitted
  bodies, donated-buffer reuse (the no-retrace and donation invariants
  of the serving engine);
* :mod:`.lock_discipline` — lock-order cycles, unlocked shared writes,
  blocking calls under a lock (the threaded serving/observability
  stack);
* :mod:`.interlock` — the interprocedural extension of lock discipline
  (held locks propagated through same-class method calls) plus thread
  lifecycle rules (unjoined threads, silent thread excepts, callbacks
  invoked under a lock);
* :mod:`.flags_metrics` — FLAGS_* registration, flag help, metric
  naming/unit-suffix conventions;
* :mod:`.clocks` — durations/deadlines must use monotonic clocks;
* :mod:`.effects` — paired effects (pages/ledger, gauge inc/dec,
  span begin/end) must release on every outgoing path, including
  exception edges;
* :mod:`.dtype_flow` — dtype flow inside resolved jitted bodies:
  promoting reductions without a cast-back, weak python scalars on
  narrow operands, wide ``np.*`` constants;
* :mod:`.shard_safety` — collectives only inside ``shard_map``-mapped
  functions on axis names the mapping binds; PartitionSpec axes
  validated against the mesh.

Entry points: ``tools/lint.py`` (CLI with committed baseline) and
:func:`paddle_tpu.analysis.run` (library).  Analyzers never import the
code they check.
"""
from .baseline import (load_baseline, load_baseline_entries, partition,
                       save_baseline)
from .core import Finding, SourceFile
from .reporters import render_json, render_text
from .runner import ALL_RULES, iter_files, run

__all__ = ["Finding", "SourceFile", "run", "iter_files", "ALL_RULES",
           "render_text", "render_json", "load_baseline",
           "load_baseline_entries", "save_baseline", "partition"]
