"""Paired-effect analyzer: an acquire must reach its release on every
outgoing path — early returns, explicit raises, AND the implicit
exception edge of any call made while the effect is held.

The serving engine is built on effect pairs whose imbalance is invisible
to tests until load: BlockManager pages (``allocate``/``allocate_seq``
vs ``free_seq``, committed-token ledger ``append`` vs ``rollback``),
inflight gauges (``.inc()`` vs ``.dec()``), and tracing spans
(``start_span`` vs ``.end()``).  Cross-function ownership transfer is
the repo's normal protocol (the scheduler allocates, ``evict`` frees),
so this analyzer only arms an acquire when the *same function* also
contains the matching release — the bug class is "cleanup written, but
only on the happy path".

Checking runs as abstract execution over the function's statement tree
with exception edges: every call made while an effect is held may
raise, and the raise edge must pass a ``finally`` that releases, or a
handler (which is then itself checked).  ``with``-statement use and
``finally``-releases are recognized as safe; a tracked span that
escapes the function (stored, returned, passed to a call, captured by
a closure) transfers ownership and stops being tracked.

Rules:

``effect-leak-on-raise``
    Pages/ledger acquired and released in one function, with an outgoing
    path (raise edge, early return, fallthrough) that skips the release.

``gauge-unpaired``
    ``X.inc()`` with a matching ``X.dec()`` in the same function that
    some path skips — the gauge drifts up under errors/cancellation.

``span-unclosed``
    A locally-bound span (``s = ...start_span(...)``) that some path
    abandons without ``s.end()`` — open spans pin the tracer ring and
    report infinite durations.
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, expr_text

__all__ = ["analyze"]

RULES = {
    "effect-leak-on-raise": "pages/ledger acquire whose same-function "
                            "release is skipped on some outgoing path",
    "gauge-unpaired": "gauge .inc() whose matching .dec() is skipped "
                      "on some outgoing path",
    "span-unclosed": "locally-bound span not .end()ed on every "
                     "outgoing path",
}

_PAGE_ACQUIRES = {"allocate", "allocate_seq"}
_PAGE_RELEASES = {"free_seq", "rollback"}
# `.append` is only a ledger acquire on a block-manager/ledger receiver
# (plain list.append is everywhere)
_LEDGER_HINTS = ("blocks", "ledger")

_HINTS = {
    "effect-leak-on-raise": "release in a `finally`, or free on the "
                            "error path before re-raising",
    "gauge-unpaired": "put the .dec() in a `finally` so errors and "
                      "early returns restore the gauge",
    "span-unclosed": "use `with span:` or end it in a `finally`",
}


def analyze(src: SourceFile) -> list[Finding]:
    text = src.text
    if ("start_span" not in text and ".inc()" not in text
            and "allocate" not in text and ".append(" not in text):
        return []                   # cheap pre-gate: nothing paired
    findings: list[Finding] = []
    for fn in _functions(src.tree):
        if _is_generator(fn):
            continue                # generator lifetime ≠ call lifetime
        if fn.name.startswith("test_"):
            continue                # tests leak/hold deliberately to
            # assert on census behavior; a failing assert aborts anyway
        _FunctionCheck(src, fn, findings).run()
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return src.filter(unique)


def _functions(tree):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _pruned_walk(node):
    """Descendants of a statement, not descending into nested defs."""
    stack = list(ast.iter_child_nodes(node))
    yield node
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _is_generator(fn) -> bool:
    return any(isinstance(n, (ast.Yield, ast.YieldFrom))
               for n in _pruned_walk(fn) if n is not fn)


def _has_call(node) -> bool:
    return any(isinstance(n, ast.Call) for n in _pruned_walk(node))


class _Effect:
    __slots__ = ("kind", "key", "line", "text", "release")

    def __init__(self, kind, key, line, text, release):
        self.kind = kind            # "pages" | "gauge" | "span"
        self.key = key              # (kind, identity-text)
        self.line = line
        self.text = text            # acquire expression, for the message
        self.release = release      # release spelling, for the message


class _Frame:
    """One enclosing ``try`` during abstract execution."""

    __slots__ = ("finally_releases", "catches", "raised_held")

    def __init__(self, finally_releases, catches):
        self.finally_releases = finally_releases   # keys released
        self.catches = catches                     # has any handler
        self.raised_held = {}       # key -> effect held at a raise edge


_RULE_OF = {"pages": "effect-leak-on-raise", "gauge": "gauge-unpaired",
            "span": "span-unclosed"}


class _FunctionCheck:
    def __init__(self, src, fn, findings):
        self.src = src
        self.fn = fn
        self.findings = findings
        self.reported: set = set()
        # same-function release inventory: an acquire is only armed when
        # its release exists somewhere in this function
        self.page_recvs: set = set()
        self.gauge_recvs: set = set()
        for node in _pruned_walk(fn):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute):
                if node.func.attr in _PAGE_RELEASES:
                    self.page_recvs.add(expr_text(node.func.value))
                elif node.func.attr == "dec":
                    self.gauge_recvs.add(expr_text(node.func.value))

    def run(self):
        held = self._run(self.fn.body, {}, [])
        if held:
            for eff in held.values():
                self._leak(eff, "when the function returns")

    # ------------------------------------------------------- execution
    def _run(self, stmts, held, frames):
        for stmt in stmts:
            held = self._stmt(stmt, held, frames)
            if held is None:
                return None
        return held

    def _stmt(self, stmt, held, frames):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            for key in self._escaped_spans(stmt, held):
                held = _without(held, key)      # closure capture
            return held
        if isinstance(stmt, ast.If):
            self._maybe_raise(stmt.test, held, frames, ())
            a = self._run(stmt.body, dict(held), frames)
            b = self._run(stmt.orelse, dict(held), frames)
            return _merge(a, b)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            head = stmt.test if isinstance(stmt, ast.While) else stmt.iter
            self._maybe_raise(head, held, frames, ())
            body_rel = self._releases_in(stmt.body)
            out = self._run(stmt.body, dict(held), frames)
            # forgiving may-release: a release inside the loop counts
            after = {k: v for k, v in held.items() if k not in body_rel}
            if out:
                for k, v in out.items():
                    after.setdefault(k, v)
            if stmt.orelse:
                after = _merge(after,
                               self._run(stmt.orelse, dict(after),
                                         frames)) or after
            return after
        if isinstance(stmt, ast.Try) or (hasattr(ast, "TryStar") and
                                         isinstance(stmt, ast.TryStar)):
            return self._try(stmt, held, frames)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, held, frames)
        if isinstance(stmt, ast.Return):
            held = dict(held)
            if stmt.value is not None:
                for key in self._escaped_spans(stmt.value, held):
                    held.pop(key, None)         # returned: caller owns it
                self._maybe_raise(stmt.value, held, frames, ())
            self._normal_exit(held, frames, "on an early return")
            return None
        if isinstance(stmt, ast.Raise):
            self._exceptional(held, frames, "on a raise")
            return None
        if isinstance(stmt, (ast.Break, ast.Continue)):
            return held             # stays inside the function
        return self._leaf(stmt, held, frames)

    def _leaf(self, stmt, held, frames):
        # escapes first: a span handed to a call transfers ownership,
        # so the handoff itself must not count as a risky raise site
        for key in self._escaped_spans(stmt, held):
            held = _without(held, key)          # ownership transferred
        rel = self._releases_in([stmt])
        if held:
            risky = {k: v for k, v in held.items() if k not in rel}
            if risky and _has_call(stmt):
                self._exceptional(risky, frames, "on an exception path")
        if rel:
            held = {k: v for k, v in held.items() if k not in rel}
        return self._acquires(stmt, held)

    # -------------------------------------------------------- acquires
    def _acquires(self, stmt, held):
        call, target = None, None
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 and \
                isinstance(stmt.value, ast.Call):
            call, target = stmt.value, stmt.targets[0]
        elif isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call):
            call = stmt.value
        if call is None or not isinstance(call.func, ast.Attribute):
            # a span var rebound to a non-span value is simply dropped
            if isinstance(stmt, ast.Assign):
                held = self._rebound(stmt, held)
            return held
        attr = call.func.attr
        recv = expr_text(call.func.value)
        eff = None
        if attr == "start_span" and isinstance(target, ast.Name):
            key = ("span", target.id)
            if key in held:         # overwritten while still open
                self._leak(held[key], "before being overwritten")
                held = _without(held, key)
            eff = _Effect("span", key, stmt.lineno,
                          f"{target.id} = ...start_span(...)", ".end()")
        elif attr in _PAGE_ACQUIRES and recv in self.page_recvs:
            eff = _Effect("pages", ("pages", recv), stmt.lineno,
                          f"{recv}.{attr}(...)", "free_seq/rollback")
        elif attr == "append" and recv in self.page_recvs and \
                any(h in recv for h in _LEDGER_HINTS):
            eff = _Effect("pages", ("pages", recv), stmt.lineno,
                          f"{recv}.append(...)", "rollback")
        elif attr == "inc" and recv in self.gauge_recvs and \
                target is None:
            eff = _Effect("gauge", ("gauge", recv), stmt.lineno,
                          f"{recv}.inc()", ".dec()")
        if eff is not None and eff.key not in held:
            held = dict(held)
            held[eff.key] = eff
        elif target is not None:
            held = self._rebound(stmt, held)
        return held

    def _rebound(self, stmt, held):
        for tgt in getattr(stmt, "targets", ()):
            if isinstance(tgt, ast.Name):
                key = ("span", tgt.id)
                if key in held:
                    self._leak(held[key], "before being overwritten")
                    held = _without(held, key)
        return held

    # ------------------------------------------- structured statements
    def _try(self, stmt, held, frames):
        fr = _Frame(self._releases_in(stmt.finalbody),
                    bool(stmt.handlers))
        body_out = self._run(stmt.body, dict(held), frames + [fr])
        if stmt.orelse and body_out is not None:
            body_out = self._run(stmt.orelse, body_out, frames + [fr])
        outs = [body_out]
        # handler exits still pass through this try's finally
        hframes = frames + [_Frame(fr.finally_releases, False)]
        for h in stmt.handlers:
            entry = dict(held)
            entry.update(fr.raised_held)
            outs.append(self._run(h.body, entry, hframes))
        merged = None
        for o in outs:
            merged = _merge(merged, o)
        if merged is None:
            self._run(stmt.finalbody, {}, frames)
            return None
        return self._run(stmt.finalbody, merged, frames)

    def _with(self, stmt, held, frames):
        for item in stmt.items:
            ce = item.context_expr
            self._maybe_raise(ce, held, frames, ())
            if isinstance(ce, ast.Name) and ("span", ce.id) in held:
                held = _without(held, ("span", ce.id))   # __exit__ ends
            else:
                for key in self._escaped_spans(ce, held):
                    held = _without(held, key)
        return self._run(stmt.body, held, frames)

    # ------------------------------------------------------- exit edges
    def _maybe_raise(self, node, held, frames, released):
        if not held or node is None:
            return
        risky = {k: v for k, v in held.items() if k not in released}
        if risky and _has_call(node):
            self._exceptional(risky, frames, "on an exception path")

    def _exceptional(self, held, frames, why):
        remaining = dict(held)
        for fr in reversed(frames):
            remaining = {k: v for k, v in remaining.items()
                         if k not in fr.finally_releases}
            if not remaining:
                return
            if fr.catches:
                fr.raised_held.update(remaining)
                return              # handler path is checked separately
        for eff in remaining.values():
            self._leak(eff, why)

    def _normal_exit(self, held, frames, why):
        protected = set()
        for fr in frames:
            protected |= fr.finally_releases
        for key, eff in held.items():
            if key not in protected:
                self._leak(eff, why)

    # --------------------------------------------------------- plumbing
    def _releases_in(self, stmts) -> set:
        out = set()
        for stmt in stmts:
            for node in _pruned_walk(stmt):
                if isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute):
                    recv, attr = node.func.value, node.func.attr
                    if attr == "end" and isinstance(recv, ast.Name):
                        out.add(("span", recv.id))
                    elif attr == "dec":
                        out.add(("gauge", expr_text(recv)))
                    elif attr in _PAGE_RELEASES:
                        out.add(("pages", expr_text(recv)))
                elif isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        ce = item.context_expr
                        if isinstance(ce, ast.Name):
                            out.add(("span", ce.id))
        return out

    def _escaped_spans(self, node, held) -> set:
        names = {key[1]: key for key, eff in held.items()
                 if eff.kind == "span"}
        if not names:
            return set()
        out: set = set()

        def visit(n):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                for sub in ast.walk(n):     # closure capture escapes
                    if isinstance(sub, ast.Name) and sub.id in names:
                        out.add(names[sub.id])
                return
            if isinstance(n, ast.Attribute) and \
                    isinstance(n.value, ast.Name):
                return                      # span.end() / span.context
            if isinstance(n, ast.Name) and n.id in names and \
                    isinstance(n.ctx, ast.Load):
                out.add(names[n.id])
                return
            for c in ast.iter_child_nodes(n):
                visit(c)

        visit(node)
        return out

    def _leak(self, eff: _Effect, why: str):
        if (eff.key, eff.line) in self.reported:
            return
        self.reported.add((eff.key, eff.line))
        rule = _RULE_OF[eff.kind]
        noun = {"pages": "acquire", "gauge": "gauge increment",
                "span": "span"}[eff.kind]
        self.findings.append(Finding(
            rule, self.src.path, eff.line,
            f"{noun} `{eff.text}` in `{self.fn.name}` is not released "
            f"by `{eff.release}` {why}",
            hint=_HINTS[rule]))


def _merge(a, b):
    if a is None:
        return None if b is None else dict(b)
    if b is None:
        return a
    out = dict(a)
    for k, v in b.items():
        out.setdefault(k, v)
    return out


def _without(held, key):
    out = dict(held)
    out.pop(key, None)
    return out
