"""Clock-discipline analyzer: durations must use monotonic clocks.

``wall-clock-duration``
    ``time.time()`` jumps under NTP slew and manual clock changes, so
    any *difference* or *deadline comparison* built from it is wrong by
    construction: spans shrink or go negative, timeouts fire early or
    never.  The repo measures durations with ``time.perf_counter()``
    (host spans, metrics) or ``time.monotonic()`` (deadlines); wall
    time is reserved for absolute "created at" stamps
    (``int(time.time())`` in API payloads) and cross-process
    timestamps, which this rule does not flag.

Flagged shapes, per function scope:

* ``time.time() - t0`` / ``time.time() + 60`` — arithmetic directly on
  a wall-clock sample;
* ``while time.time() < deadline`` — comparison on a sample;
* ``now = time.time(); ... now - ts`` — arithmetic/comparison on a
  local name bound to a wall-clock sample in the same scope.

Legitimate cross-process wall-clock comparisons (e.g. TTL checks on
heartbeats written by another host) carry an explicit
``# tpu-lint: disable=wall-clock-duration`` suppression.
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, call_name, expr_text

__all__ = ["analyze"]

RULES = {
    "wall-clock-duration": "duration/deadline computed from time.time() "
                           "instead of a monotonic clock",
}

_WALL_CALLS = ("time.time", "_time.time")


def analyze(src: SourceFile) -> list[Finding]:
    if ".time()" not in src.text:   # cheap pre-gate: no wall samples
        return []
    findings: list[Finding] = []
    seen_lines: set[int] = set()
    for scope in _scopes(src.tree):
        wall_names = _wall_assigned_names(scope)
        for node in _scoped_nodes(scope):
            expr = None
            if isinstance(node, ast.BinOp) and \
                    isinstance(node.op, (ast.Add, ast.Sub)):
                if _is_wall(node.left, wall_names) or \
                        _is_wall(node.right, wall_names):
                    expr = node
            elif isinstance(node, ast.Compare):
                operands = [node.left] + list(node.comparators)
                if any(_is_wall(o, wall_names) for o in operands):
                    expr = node
            if expr is not None and expr.lineno not in seen_lines:
                seen_lines.add(expr.lineno)
                findings.append(Finding(
                    "wall-clock-duration", src.path, expr.lineno,
                    f"`{expr_text(expr)}` computes a duration/deadline "
                    "from time.time(), which jumps under NTP slew",
                    hint="use time.monotonic() for deadlines or "
                         "time.perf_counter() for measured spans; keep "
                         "time.time() only for absolute 'created' "
                         "stamps"))
    return src.filter(findings)


def _scopes(tree):
    """Module plus every function, each yielded once as a scope root."""
    yield tree
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _scoped_nodes(scope):
    """Descendants of a scope, pruning nested function bodies — they
    are their own scope (yielded separately by :func:`_scopes`)."""
    for child in ast.iter_child_nodes(scope):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield child
        yield from _scoped_nodes(child)


def _wall_assigned_names(scope) -> set:
    """Local names bound directly to a ``time.time()`` sample."""
    names = set()
    for node in _scoped_nodes(scope):
        value = None
        targets = []
        if isinstance(node, ast.Assign):
            value, targets = node.value, node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            value, targets = node.value, [node.target]
        if value is None:
            continue
        if isinstance(value, ast.Call) and \
                call_name(value) in _WALL_CALLS:
            for tgt in targets:
                names.add(expr_text(tgt))
    return names


def _is_wall(node, wall_names) -> bool:
    if isinstance(node, ast.Call) and call_name(node) in _WALL_CALLS:
        return True
    if isinstance(node, (ast.Name, ast.Attribute)) and \
            expr_text(node) in wall_names:
        return True
    return False
