"""Text and JSON rendering of findings."""
from __future__ import annotations

import json

from .core import Finding

__all__ = ["render_text", "render_json"]


def render_text(findings: list[Finding], baselined: int = 0) -> str:
    """One line per finding, sorted by location, plus a summary line."""
    lines = [f.render() for f in
             sorted(findings, key=lambda f: (f.path, f.line, f.rule))]
    by_rule: dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    summary = (f"{len(findings)} finding"
               f"{'' if len(findings) == 1 else 's'}")
    if by_rule:
        summary += " (" + ", ".join(
            f"{n} {r}" for r, n in sorted(by_rule.items())) + ")"
    if baselined:
        summary += f"; {baselined} baselined finding" \
                   f"{'' if baselined == 1 else 's'} suppressed"
    lines.append(summary)
    return "\n".join(lines)


def render_json(findings: list[Finding], baselined: int = 0) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in
                      sorted(findings,
                             key=lambda f: (f.path, f.line, f.rule))],
         "count": len(findings),
         "baselined": baselined},
        indent=2) + "\n"
