"""Baseline load/save/compare.

The committed baseline (``tools/lint_baseline.json``) records the
accepted pre-existing findings by *fingerprint* — a hash of
(rule, path, message) that deliberately excludes the line number, so
editing code above a known finding does not resurrect it.  The gate
fails only on findings whose fingerprint count exceeds the baselined
count: fixing one of two identical findings stays green, adding a third
fails.
"""
from __future__ import annotations

import json
import os

from .core import Finding

__all__ = ["load_baseline", "save_baseline", "partition"]

_VERSION = 1


def load_baseline(path: str) -> dict[str, int]:
    """fingerprint -> accepted count.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    counts: dict[str, int] = {}
    for entry in data.get("findings", []):
        fp = entry["fingerprint"]
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def save_baseline(path: str, findings: list[Finding]) -> None:
    """Every finding, with rule id + location, human-reviewable."""
    data = {
        "version": _VERSION,
        "comment": "Accepted pre-existing lint findings. Regenerate "
                   "deliberately with `python tools/lint.py "
                   "--update-baseline`; never hand-edit counts.",
        "findings": [f.to_dict() for f in
                     sorted(findings,
                            key=lambda f: (f.path, f.line, f.rule))],
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def partition(findings: list[Finding],
              baseline: dict[str, int]) -> tuple[list[Finding],
                                                 list[Finding]]:
    """(new, baselined).  Within one fingerprint, the first N
    occurrences (source order) are baselined, the excess is new."""
    remaining = dict(baseline)
    new, old = [], []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
