"""Baseline load/save/compare.

The committed baseline (``tools/lint_baseline.json``) records the
accepted pre-existing findings by *fingerprint* — a hash of
(rule, path, message) that deliberately excludes the line number, so
editing code above a known finding does not resurrect it.  The gate
fails only on findings whose fingerprint count exceeds the baselined
count: fixing one of two identical findings stays green, adding a third
fails.
"""
from __future__ import annotations

import json
import os

from .core import Finding

__all__ = ["load_baseline", "load_baseline_entries", "save_baseline",
           "partition"]

_VERSION = 1


def load_baseline(path: str) -> dict[str, int]:
    """fingerprint -> accepted count.  Missing file = empty baseline."""
    counts: dict[str, int] = {}
    for entry in load_baseline_entries(path):
        fp = entry["fingerprint"]
        counts[fp] = counts.get(fp, 0) + 1
    return counts


def load_baseline_entries(path: str) -> list[dict]:
    """The raw finding entries (rule/path/line/message/fingerprint and
    an optional hand-written ``why`` justification).  Missing file =
    empty list."""
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def save_baseline(path: str, findings) -> None:
    """Every finding, with rule id + location, human-reviewable.
    Accepts :class:`Finding` objects and raw baseline entry dicts
    interchangeably (the merge path re-saves entries it kept), and
    preserves any ``why`` justification keys on dict entries."""
    entries = [f.to_dict() if isinstance(f, Finding) else dict(f)
               for f in findings]
    data = {
        "version": _VERSION,
        "comment": "Accepted pre-existing lint findings. Regenerate "
                   "deliberately with `python tools/lint.py "
                   "--update-baseline`; never hand-edit counts. "
                   "`why` keys are hand-written justifications and "
                   "survive --update-baseline by fingerprint.",
        "findings": sorted(entries,
                           key=lambda e: (e["path"], e["line"],
                                          e["rule"])),
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def partition(findings: list[Finding],
              baseline: dict[str, int]) -> tuple[list[Finding],
                                                 list[Finding]]:
    """(new, baselined).  Within one fingerprint, the first N
    occurrences (source order) are baselined, the excess is new."""
    remaining = dict(baseline)
    new, old = [], []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        if remaining.get(f.fingerprint, 0) > 0:
            remaining[f.fingerprint] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
