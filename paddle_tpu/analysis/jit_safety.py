"""Jit-safety analyzer: host syncs, traced branches, donated reuse.

Resolves every function handed to ``jax.jit`` in a module — named
functions, lambdas, decorated defs, and factory patterns like
``jax.jit(self._build_step())`` where a method returns a closure — and
checks the *traced body* for the failure modes that only surface at
runtime as a hang or a silent retrace:

``jit-host-sync``
    Calls that force a device->host transfer or only run at trace time:
    ``np.asarray``/``np.array`` on traced values, ``.item()`` /
    ``.block_until_ready()`` / ``jax.device_get`` on traced values,
    ``print``, and ``time.*`` (a ``time.time()`` inside a jitted body
    samples the clock ONCE at trace time — it measures nothing), plus
    ``float()/int()/bool()`` casts of traced values (each is a
    blocking concretization).

``jit-traced-branch``
    Python ``if`` / ``while`` / ternary on a traced value — a
    ``TracerBoolConversionError`` at best, a silent per-value retrace
    via static_argnums at worst.  Branching on shapes/dtypes/ndim is
    static and allowed.

``jit-donated-reuse``
    A buffer passed at a ``donate_argnums`` position is dead after the
    call; reading it again aliases freed device memory.  The check
    flags call sites where a donated argument is used later in the
    function without first being rebound (typically from the call's own
    results).

Tracedness is a per-function taint: parameters (minus static_argnums)
and anything derived from them or from ``jnp.*`` results.  Shape/dtype
attribute reads (``x.shape``, ``x.ndim``, ``x.dtype``, ``len(x)``)
launder the taint — branching on those is legitimate.  Helper functions
called from a jitted body that are defined in the same module are
analyzed transitively (depth-bounded).
"""
from __future__ import annotations

import ast

from .core import Finding, SourceFile, call_name, expr_text

__all__ = ["analyze"]

RULES = {
    "jit-host-sync": "host sync / trace-time-only call inside a jitted "
                     "function",
    "jit-traced-branch": "python control flow on a traced value inside "
                         "a jitted function",
    "jit-donated-reuse": "donated buffer used after the jit call "
                         "without rebinding",
}

# calls that are wrong inside a jitted body regardless of their argument
_ALWAYS_BAD_CALLS = {
    "print": "runs at trace time only — use jax.debug.print",
    "time.time": "samples the clock once at trace time",
    "time.monotonic": "samples the clock once at trace time",
    "time.perf_counter": "samples the clock once at trace time",
    "time.sleep": "blocks tracing, never the compiled step",
}

# calls that are host syncs when applied to a traced value
_TAINTED_BAD_CALLS = {
    "np.asarray": "forces a device->host transfer mid-program",
    "np.array": "forces a device->host transfer mid-program",
    "numpy.asarray": "forces a device->host transfer mid-program",
    "numpy.array": "forces a device->host transfer mid-program",
    "jax.device_get": "forces a device->host transfer mid-program",
}

_TAINTED_BAD_METHODS = {
    "item": "concretizes a traced value (blocking transfer)",
    "block_until_ready": "host sync inside the traced program",
    "tolist": "concretizes a traced value (blocking transfer)",
}

_CASTS = {"float", "int", "bool"}

# attribute reads that yield static (trace-time) values: branching on
# them is fine and must not propagate taint
_SHAPE_ATTRS = {"shape", "ndim", "dtype", "size", "maxlen"}
_UNTAINT_CALLS = {"len", "range", "isinstance", "getattr", "hasattr",
                  "enumerate", "zip"}

_MAX_DEPTH = 2

# wrappers that forward tracing to their first argument: resolving
# through them lets `jax.jit(jax.shard_map(step, ...))` and the local
# `mapped = jax.shard_map(...); return jax.jit(mapped)` idiom reach the
# real body
_WRAPPER_CALLS = {"jax.shard_map", "shard_map",
                  "jax.experimental.shard_map.shard_map",
                  "functools.partial", "partial"}


def analyze(src: SourceFile) -> list[Finding]:
    if "jit" not in src.text:       # cheap pre-gate: nothing to resolve
        return []
    mod = _ModuleIndex(src)
    findings: list[Finding] = []
    for jit in mod.jit_calls:
        body = mod.resolve_target(jit)
        if body is not None and id(body.node) not in mod.analyzed:
            mod.analyzed.add(id(body.node))
            findings.extend(_check_traced_body(src, mod, body, depth=0))
    findings.extend(_check_donated_reuse(src, mod))
    seen, unique = set(), []
    for f in findings:
        key = (f.rule, f.path, f.line, f.message)
        if key not in seen:
            seen.add(key)
            unique.append(f)
    return src.filter(unique)


# --------------------------------------------------------------- indexing
class _JitCall:
    """One ``jax.jit(...)`` call site and its surroundings."""

    def __init__(self, call, enclosing_func, enclosing_class):
        self.call = call
        self.func = enclosing_func          # FunctionDef | None
        self.cls = enclosing_class          # ClassDef | None
        self.donate = _donate_argnums(call)
        self.static = _static_argnums(call)


class _Resolved:
    """A function body to be treated as traced."""

    def __init__(self, node, params, static_idx):
        self.node = node                    # FunctionDef | Lambda
        self.params = params                # ordered param names
        self.static_idx = static_idx        # set of static positions


class _ModuleIndex:
    """Scopes, defs, and jit bindings of one module."""

    def __init__(self, src: SourceFile):
        self.src = src
        self.jit_calls: list[_JitCall] = []
        self.analyzed: set[int] = set()
        # (class name | None, func name) -> FunctionDef
        self.defs: dict[tuple, ast.AST] = {}
        # nested defs: id(parent FunctionDef) -> {name: FunctionDef}
        self.nested: dict[int, dict] = {}
        # jit bindings for the donated-reuse check
        self.attr_donate: dict[str, tuple] = {}     # self.X = jax.jit(..)
        self.factory_donate: dict[str, tuple] = {}  # def F(): return jit
        self.decorated_donate: dict[str, tuple] = {}
        self.module_donate: dict[str, tuple] = {}   # X = jax.jit(..)
        self._walk(src.tree, None, None)
        self._index_bindings()

    def _walk(self, node, func, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                self._walk(child, None, child)
            elif isinstance(child, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                self.defs[(cls.name if cls else None, child.name)] = child
                if func is not None:
                    self.nested.setdefault(id(func), {})[child.name] = \
                        child
                for dec in child.decorator_list:
                    if (isinstance(dec, ast.Call)
                            and call_name(dec) in ("jax.jit", "jit")) or \
                            (not isinstance(dec, ast.Call)
                             and expr_text(dec) in ("jax.jit", "jit")):
                        call = dec if isinstance(dec, ast.Call) else None
                        donate = _donate_argnums(call) if call else ()
                        static = _static_argnums(call) if call else set()
                        self.decorated_donate[child.name] = donate
                        jc = _JitCall(call or ast.Call(
                            func=ast.Name(id="jit", ctx=ast.Load()),
                            args=[], keywords=[]), func, cls)
                        jc._decorated = child
                        jc.static = static
                        self.jit_calls.append(jc)
                self._walk(child, child, cls)
            else:
                for sub in ast.walk(child):
                    if isinstance(sub, ast.Call) and \
                            call_name(sub) in ("jax.jit", "jit"):
                        self.jit_calls.append(_JitCall(sub, func, cls))
                    elif isinstance(sub, (ast.FunctionDef,
                                          ast.AsyncFunctionDef,
                                          ast.Lambda)):
                        break

    # -------------------------------------------------------- resolution
    def resolve_target(self, jit: _JitCall) -> _Resolved | None:
        dec = getattr(jit, "_decorated", None)
        if dec is not None:
            return _resolved_from_def(dec, jit.static)
        if not jit.call.args:
            return None
        return self._resolve_expr(jit.call.args[0], jit)

    def _resolve_expr(self, target, jit: _JitCall, depth=0):
        if depth > 3:
            return None
        if isinstance(target, ast.Lambda):
            params = [a.arg for a in target.args.args]
            return _Resolved(target, params, jit.static)
        if isinstance(target, ast.Name):
            fn = self._lookup(target.id, jit)
            if fn is not None:
                return _resolved_from_def(fn, jit.static)
            # local wrapper binding: `mapped = jax.shard_map(step, ...)`
            # then `jax.jit(mapped, ...)`
            bound = self._local_assign(target.id, jit)
            if bound is not None and isinstance(bound, ast.Call) and \
                    call_name(bound) in _WRAPPER_CALLS and bound.args:
                return self._resolve_expr(bound.args[0], jit, depth + 1)
            return None
        if isinstance(target, ast.Call):
            name = call_name(target)
            # transparent wrappers: jax.jit(jax.shard_map(step, ...))
            if name in _WRAPPER_CALLS and target.args:
                return self._resolve_expr(target.args[0], jit, depth + 1)
            # factory pattern: jax.jit(self._build_step())
            if name is None:
                return None
            base = name.split(".")[-1]
            fn = self._lookup(base, jit)
            if fn is None and name.startswith("self.") and jit.cls:
                fn = self.defs.get((jit.cls.name, base))
            if fn is None:
                return None
            inner = self._returned_function(fn)
            if inner is not None:
                return _resolved_from_def(inner, jit.static)
        return None

    def _lookup(self, name, jit: _JitCall):
        if jit.func is not None:
            fn = self.nested.get(id(jit.func), {}).get(name)
            if fn is not None:
                return fn
        if jit.cls is not None:
            fn = self.defs.get((jit.cls.name, name))
            if fn is not None:
                return fn
        return self.defs.get((None, name))

    def _local_assign(self, name, jit: _JitCall):
        """The value last assigned to `name` in the jit call's enclosing
        function, if it is a plain single-target assignment."""
        if jit.func is None:
            return None
        found = None
        for node in ast.walk(jit.func):
            if isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name) and \
                    node.targets[0].id == name and \
                    node.lineno <= jit.call.lineno:
                found = node.value
        return found

    def _returned_function(self, fn):
        """The FunctionDef/Lambda a factory returns, if statically
        resolvable."""
        locals_ = self.nested.get(id(fn), {})
        for node in ast.walk(fn):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            v = node.value
            if isinstance(v, ast.Lambda):
                return v
            if isinstance(v, ast.Name) and v.id in locals_:
                return locals_[v.id]
            if isinstance(v, ast.Call) and \
                    call_name(v) in ("jax.jit", "jit") and v.args:
                inner = v.args[0]
                if isinstance(inner, ast.Lambda):
                    return inner
                if isinstance(inner, ast.Name) and inner.id in locals_:
                    return locals_[inner.id]
        return None

    # ---------------------------------------------- donated-reuse bindings
    def _index_bindings(self):
        for jit in self.jit_calls:
            donate = jit.donate
            if not donate:
                continue
            stmt = getattr(jit, "_decorated", None)
            if stmt is not None:
                continue
            parent = _assign_parent(self.src.tree, jit.call)
            if parent is None:
                continue
            for tgt in getattr(parent, "targets", []) or \
                    ([parent.target] if isinstance(
                        parent, (ast.AnnAssign, ast.AugAssign)) else []):
                text = expr_text(tgt)
                if text.startswith("self."):
                    self.attr_donate[text[5:]] = donate
                elif isinstance(tgt, ast.Name) and jit.func is None:
                    self.module_donate[tgt.id] = donate
                elif isinstance(tgt, ast.Name) and jit.func is not None:
                    # a local jit binding; if the enclosing function
                    # returns it, the function is a jit factory
                    for node in ast.walk(jit.func):
                        if isinstance(node, ast.Return) and \
                                isinstance(node.value, ast.Name) and \
                                node.value.id == tgt.id:
                            self.factory_donate[jit.func.name] = donate
            # `return jax.jit(...)` directly
            ret = _return_parent(self.src.tree, jit.call)
            if ret is not None and jit.func is not None:
                self.factory_donate[jit.func.name] = donate


def _resolved_from_def(fn, static):
    if isinstance(fn, ast.Lambda):
        return _Resolved(fn, [a.arg for a in fn.args.args], static)
    params = [a.arg for a in fn.args.args
              if a.arg not in ("self", "cls")]
    return _Resolved(fn, params, static)


def _donate_argnums(call) -> tuple:
    for kw in call.keywords if call is not None else ():
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant):
                        out.append(e.value)
                return tuple(out)
            if isinstance(v, ast.Constant):
                return (v.value,)
            return ()               # dynamic (conditional) — skip check
    return ()


def _static_argnums(call) -> set:
    for kw in call.keywords if call is not None else ():
        if kw.arg == "static_argnums":
            v = kw.value
            if isinstance(v, (ast.Tuple, ast.List)):
                return {e.value for e in v.elts
                        if isinstance(e, ast.Constant)}
            if isinstance(v, ast.Constant):
                return {v.value}
    return set()


def _assign_parent(tree, call):
    for node in ast.walk(tree):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                getattr(node, "value", None) is call:
            return node
    return None


def _return_parent(tree, call):
    for node in ast.walk(tree):
        if isinstance(node, ast.Return) and node.value is call:
            return node
    return None


# ------------------------------------------------------------ taint check
def _check_traced_body(src, mod: _ModuleIndex, body: _Resolved,
                       depth: int) -> list[Finding]:
    findings: list[Finding] = []
    node = body.node
    stmts = node.body if isinstance(node, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)) \
        else [ast.Expr(value=node.body)]
    tainted = {p for i, p in enumerate(body.params)
               if i not in body.static_idx}
    # two propagation passes: handles use-before-def across loop bodies
    for _ in range(2):
        for stmt in stmts:
            _propagate(stmt, tainted)

    for sub in ast.walk(node if isinstance(node, ast.Lambda)
                        else ast.Module(body=stmts, type_ignores=[])):
        if isinstance(sub, ast.Call):
            findings.extend(_check_call(src, mod, sub, tainted, depth))
        elif isinstance(sub, (ast.If, ast.While)):
            if _branch_tainted(sub.test, tainted):
                kind = "if" if isinstance(sub, ast.If) else "while"
                findings.append(Finding(
                    "jit-traced-branch", src.path, sub.lineno,
                    f"python `{kind}` on traced value "
                    f"`{expr_text(sub.test)}` inside a jitted function",
                    hint="use jnp.where / lax.cond / lax.while_loop, or "
                         "mark the driver static"))
        elif isinstance(sub, ast.IfExp):
            if _branch_tainted(sub.test, tainted):
                findings.append(Finding(
                    "jit-traced-branch", src.path, sub.lineno,
                    f"ternary on traced value `{expr_text(sub.test)}` "
                    "inside a jitted function",
                    hint="use jnp.where / lax.cond"))
    return findings


def _check_call(src, mod, call, tainted, depth) -> list[Finding]:
    name = call_name(call)
    out: list[Finding] = []
    loc = call.lineno
    if name in _ALWAYS_BAD_CALLS:
        out.append(Finding(
            "jit-host-sync", src.path, loc,
            f"`{name}(...)` inside a jitted function: "
            f"{_ALWAYS_BAD_CALLS[name]}",
            hint="move it outside the traced body"))
        return out
    if name in _TAINTED_BAD_CALLS and call.args and \
            _is_tainted(call.args[0], tainted):
        out.append(Finding(
            "jit-host-sync", src.path, loc,
            f"`{name}({expr_text(call.args[0])})` on a traced value: "
            f"{_TAINTED_BAD_CALLS[name]}",
            hint="keep the value on device (jnp) or return it and "
                 "convert outside the jit"))
        return out
    if name in _CASTS and call.args and \
            _is_tainted(call.args[0], tainted):
        out.append(Finding(
            "jit-host-sync", src.path, loc,
            f"`{name}({expr_text(call.args[0])})` concretizes a traced "
            "value (blocking host sync)",
            hint="use .astype / jnp casts, or compute it outside the "
                 "jitted body"))
        return out
    if isinstance(call.func, ast.Attribute) and \
            call.func.attr in _TAINTED_BAD_METHODS and \
            _is_tainted(call.func.value, tainted):
        out.append(Finding(
            "jit-host-sync", src.path, loc,
            f"`.{call.func.attr}()` on traced value "
            f"`{expr_text(call.func.value)}`: "
            f"{_TAINTED_BAD_METHODS[call.func.attr]}",
            hint="return the array and concretize outside the jit"))
        return out
    # transitive: same-module helper called with traced arguments — only
    # the positions that actually receive a traced value are tainted
    # (config objects etc. passed alongside stay static)
    if depth < _MAX_DEPTH and name is not None and "." not in name:
        fn = mod.defs.get((None, name))
        if fn is not None and id(fn) not in mod.analyzed:
            traced_pos = {i for i, a in enumerate(call.args)
                          if _is_tainted(a, tainted)}
            if traced_pos:
                mod.analyzed.add(id(fn))
                nparams = len(fn.args.args)
                static = set(range(nparams)) - traced_pos
                out.extend(_check_traced_body(
                    src, mod, _resolved_from_def(fn, static),
                    depth + 1))
    return out


def _propagate(stmt, tainted: set):
    """One pass of name-level taint propagation through a statement."""
    for node in ast.walk(stmt):
        if isinstance(node, ast.Assign):
            if _is_tainted(node.value, tainted):
                for tgt in node.targets:
                    _taint_target(tgt, tainted)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            if node.value is not None and \
                    _is_tainted(node.value, tainted):
                _taint_target(node.target, tainted)
        elif isinstance(node, ast.For):
            if _is_tainted(node.iter, tainted):
                _taint_target(node.target, tainted)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            continue


def _taint_target(tgt, tainted: set):
    for node in ast.walk(tgt):
        if isinstance(node, ast.Name):
            tainted.add(node.id)


def _branch_tainted(test, tainted: set) -> bool:
    """Tainted-for-branching: identity/membership tests (``x is None``,
    ``k in params``) inspect pytree *structure* or dict *keys*, both
    static at trace time, so they never make a branch illegal."""
    if isinstance(test, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn))
            for op in test.ops):
        return False
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
        return _branch_tainted(test.operand, tainted)
    if isinstance(test, ast.BoolOp):
        return any(_branch_tainted(v, tainted) for v in test.values)
    return _is_tainted(test, tainted)


def _is_tainted(expr, tainted: set) -> bool:
    """Does this expression carry a traced value?  Shape/dtype reads and
    their derivations are static and do not count."""
    return _taint_of(expr, tainted)


def _taint_of(node, tainted) -> bool:
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute):
        if node.attr in _SHAPE_ATTRS:
            return False            # x.shape / x.ndim are static
        return _taint_of(node.value, tainted)
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in _UNTAINT_CALLS:
            return False            # len(x), range(...), isinstance(..)
        base = (name or "").split(".")[0]
        if base in ("jnp", "lax", "jax"):
            return True             # jnp.* results are traced
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in _SHAPE_ATTRS:
            return False
        return any(_taint_of(a, tainted) for a in node.args) or \
            any(_taint_of(kw.value, tainted) for kw in node.keywords) or \
            _taint_of(node.func, tainted)
    if isinstance(node, ast.Subscript):
        if isinstance(node.value, ast.Attribute) and \
                node.value.attr in _SHAPE_ATTRS:
            return False            # x.shape[0]
        return _taint_of(node.value, tainted) or \
            _taint_of(node.slice, tainted)
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        return any(_taint_of(e, tainted) for e in node.elts)
    if isinstance(node, ast.Dict):
        return any(_taint_of(v, tainted)
                   for v in node.values if v is not None)
    if isinstance(node, ast.BoolOp):
        return any(_taint_of(v, tainted) for v in node.values)
    if isinstance(node, ast.BinOp):
        return _taint_of(node.left, tainted) or \
            _taint_of(node.right, tainted)
    if isinstance(node, ast.UnaryOp):
        return _taint_of(node.operand, tainted)
    if isinstance(node, ast.Compare):
        return _taint_of(node.left, tainted) or \
            any(_taint_of(c, tainted) for c in node.comparators)
    if isinstance(node, ast.IfExp):
        return _taint_of(node.body, tainted) or \
            _taint_of(node.orelse, tainted)
    if isinstance(node, ast.Starred):
        return _taint_of(node.value, tainted)
    if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
        return False
    return False


# -------------------------------------------------------- donated reuse
def _check_donated_reuse(src, mod: _ModuleIndex) -> list[Finding]:
    findings: list[Finding] = []
    nested_ids = {id(f) for locals_ in mod.nested.values()
                  for f in locals_.values()}
    for (cls, name), fn in mod.defs.items():
        if id(fn) in nested_ids:
            continue        # covered by the walk of its enclosing def
        findings.extend(_reuse_in_function(src, mod, fn))
    return findings


def _reuse_in_function(src, mod, fn) -> list[Finding]:
    out: list[Finding] = []
    # local jit bindings, flow-sensitive: (name, line) -> donate tuple,
    # so `fn = self._prefill_fn(b)` and a later `fn = ...cached_fn(b)`
    # each govern only the calls between them
    local_binds: dict[str, list] = {}       # name -> [(line, donate)]
    for node in ast.walk(fn):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        call = node.value
        cname = call_name(call) or ""
        donate = None
        if cname in ("jax.jit", "jit"):
            donate = _donate_argnums(call)
        else:
            base = cname.split(".")[-1]
            if cname.startswith("self.") and \
                    base in mod.factory_donate:
                donate = mod.factory_donate[base]
            elif base in mod.factory_donate and "." not in cname:
                donate = mod.factory_donate[base]
        for tgt in node.targets:
            if isinstance(tgt, ast.Name):
                local_binds.setdefault(tgt.id, []).append(
                    (node.lineno, donate or ()))
    for binds in local_binds.values():
        binds.sort()

    for call in ast.walk(fn):
        if not isinstance(call, ast.Call):
            continue
        cname = call_name(call)
        if cname is None:
            continue
        donate = None
        if cname.startswith("self.") and cname[5:] in mod.attr_donate:
            donate = mod.attr_donate[cname[5:]]
        elif cname in local_binds:
            # the binding in effect at this call site: the last
            # assignment on a line at or before it
            for line, d in local_binds[cname]:
                if line <= call.lineno:
                    donate = d
        elif cname in mod.decorated_donate:
            donate = mod.decorated_donate[cname]
        elif cname in mod.module_donate:
            donate = mod.module_donate[cname]
        if not donate:
            continue
        out.extend(_reuse_at_call(src, fn, call, donate))
    return out


def _reuse_at_call(src, fn, call, donate) -> list[Finding]:
    out: list[Finding] = []
    rebound = _rebound_targets(fn, call)
    for idx in donate:
        if not isinstance(idx, int) or idx >= len(call.args):
            continue
        arg = call.args[idx]
        if not isinstance(arg, (ast.Name, ast.Attribute)):
            continue                # temporaries cannot be reused later
        text = expr_text(arg)
        if text in rebound:
            continue
        use = _first_use_after(fn, call, text)
        if use is not None and isinstance(use.ctx, ast.Load):
            out.append(Finding(
                "jit-donated-reuse", src.path, use.lineno,
                f"`{text}` was donated to `{call_name(call)}` at "
                f"{src.path.rsplit('/', 1)[-1]}:{call.lineno} "
                f"(donate_argnums index {idx}) and is read again "
                "without being rebound",
                hint="rebind it from the call's results "
                     "(`x, ... = fn(x, ...)`) or drop it from "
                     "donate_argnums"))
    return out


def _rebound_targets(fn, call) -> set:
    """Expression texts assigned by the statement containing `call`."""
    for stmt in ast.walk(fn):
        if isinstance(stmt, ast.Assign):
            contains = any(n is call for n in ast.walk(stmt.value))
            if contains:
                texts = set()
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Tuple):
                        texts.update(expr_text(e) for e in tgt.elts)
                    else:
                        texts.add(expr_text(tgt))
                return texts
    return set()


def _first_use_after(fn, call, text):
    """First Name/Attribute node matching `text` positioned strictly
    after the call expression, in source order."""
    end = (call.end_lineno or call.lineno,
           call.end_col_offset or call.col_offset)
    best = None
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Name, ast.Attribute)):
            continue
        pos = (node.lineno, node.col_offset)
        if pos <= end:
            continue
        if expr_text(node) != text:
            continue
        if best is None or pos < (best.lineno, best.col_offset):
            best = node
    return best
