"""Flags / metrics conformance analyzer.

Flags (the ``paddle_tpu.flags`` registry is the single source of truth;
definitions are parsed from ``flags.py`` itself, never imported):

``flag-undefined``
    A ``"FLAGS_*"`` string constant used anywhere in code — a
    ``FLAGS[...]`` / ``FLAGS.get(...)`` read, a ``set_flags`` key, or
    an env-dict export like ``{"FLAGS_selected_devices": ...}`` — that
    no ``define_flag`` call registers.  A typo'd flag name otherwise
    reads as permanently-default and fails silently.

``flag-missing-help``
    ``define_flag`` without non-empty help text.  ~243 flags in the
    reference all carry help; ours do too.

``flag-duplicate``
    The same flag name registered by two ``define_flag`` calls.

Metrics (names are a public scrape interface; Prometheus conventions):

``metric-name``
    Registration with a literal name that is not ``[a-z][a-z0-9_]*`` or
    does not start with one of the repo's subsystem prefixes
    (``serving_``, ``router_``, ``eager_``, ``hapi_``, ``device_``,
    ``host_``, ``comm_``, ``collective_``, ``obs_``).

``metric-suffix``
    Unit-suffix conventions: counters end ``_total``; histograms end
    ``_seconds`` or ``_bytes``; gauges must NOT end ``_total`` (that
    suffix promises monotonicity to every PromQL ``rate()`` user).

``metric-duplicate``
    The same metric name registered with two different kinds — the
    registry raises at runtime; this catches it before any process
    does.

``metric-unbounded-label``
    A ``.labels(...)`` argument tainted from a request/header-derived
    string (``*.headers.get(...)``, ``*.headers[...]``) without first
    passing through a bounding map.  Every distinct label value
    allocates a metric child forever, so a caller-controlled string is
    an unbounded-cardinality (memory + scrape-size) leak.  Taint flows
    through plain name assignment, ``str()``, string passthroughs
    (``.strip()``/``.lower()``/...), f-strings, concatenation, and
    ``or``-defaults; any other call — a table lookup, a canonicalizer —
    bounds the value and clears it.

Metric rules only apply outside ``tests/`` (tests register throwaway
names on private registries deliberately); flag rules apply everywhere.
"""
from __future__ import annotations

import ast
import re

from .core import Finding, SourceFile, call_name

__all__ = ["FlagsMetricsAnalyzer", "collect_flag_defs"]

RULES = {
    "flag-undefined": "FLAGS_* name used but never define_flag-registered",
    "flag-missing-help": "define_flag without help text",
    "flag-duplicate": "flag registered twice",
    "metric-name": "metric name violates naming conventions",
    "metric-suffix": "metric name violates unit-suffix conventions "
                     "(_total/_seconds/_bytes)",
    "metric-duplicate": "metric name registered with two different kinds",
    "metric-unbounded-label": "metric label fed from a request/header "
                              "string without a bounding map",
}

_FLAG_RE = re.compile(r"^FLAGS_[A-Za-z0-9_]+$")
_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

METRIC_PREFIXES = ("serving_", "router_", "eager_", "hapi_", "device_",
                   "host_", "comm_", "collective_", "obs_")

_HISTO_SUFFIXES = ("_seconds", "_bytes")


def collect_flag_defs(src: SourceFile):
    """(name, has_help, lineno) for every ``define_flag`` call."""
    out = []
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node) or ""
        if name.rsplit(".", 1)[-1] != "define_flag":
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) \
                or not isinstance(node.args[0].value, str):
            continue
        flag = node.args[0].value
        help_arg = node.args[2] if len(node.args) > 2 else None
        for kw in node.keywords:
            if kw.arg == "help_":
                help_arg = kw.value
        has_help = not (help_arg is None or
                        (isinstance(help_arg, ast.Constant) and
                         not str(help_arg.value).strip()))
        out.append((flag, has_help, node.lineno))
    return out


class FlagsMetricsAnalyzer:
    """Stateful across files: flag registry + seen metric kinds."""

    def __init__(self, flag_defs=None):
        # flag name -> (has_help, "path:line")
        self.flags: dict[str, tuple] = dict(flag_defs or {})
        # metric name -> (kind, "path:line")
        self.metrics: dict[str, tuple] = {}

    def check(self, src: SourceFile) -> list[Finding]:
        findings: list[Finding] = []
        if "FLAGS_" in src.text:        # cheap pre-gates
            def_lines = self._check_definitions(src, findings)
            self._check_flag_reads(src, findings, def_lines)
        if not _is_test_path(src.path) and any(
                k + "(" in src.text
                for k in ("counter", "gauge", "histogram")):
            self._check_metrics(src, findings)
        if not _is_test_path(src.path) and ".labels(" in src.text:
            self._check_label_taint(src, findings)
        return src.filter(findings)

    # ------------------------------------------------------------- flags
    def _check_definitions(self, src, findings) -> set:
        """Validate define_flag sites; returns the AST positions of the
        name constants so the read scan skips them."""
        def_positions = set()
        help_by_name = {f: h for f, h, _ln in collect_flag_defs(src)}
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = (call_name(node) or "").rsplit(".", 1)[-1]
            if cname != "define_flag" or not node.args:
                continue
            arg0 = node.args[0]
            if not (isinstance(arg0, ast.Constant) and
                    isinstance(arg0.value, str)):
                continue
            def_positions.add((arg0.lineno, arg0.col_offset))
            flag = arg0.value
            loc = f"{src.path}:{node.lineno}"
            if flag in self.flags:
                findings.append(Finding(
                    "flag-duplicate", src.path, node.lineno,
                    f"flag {flag!r} already registered at "
                    f"{self.flags[flag][1]}",
                    hint="drop one of the registrations"))
                continue
            has_help = help_by_name.get(flag, False)
            self.flags[flag] = (has_help, loc)
            if not has_help:
                findings.append(Finding(
                    "flag-missing-help", src.path, node.lineno,
                    f"flag {flag!r} registered without help text",
                    hint="every flag carries help; it is the only "
                         "documentation set_flags users see"))
        return def_positions

    def _check_flag_reads(self, src, findings, def_positions):
        doc_positions = _docstring_positions(src.tree)
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Constant) and
                    isinstance(node.value, str) and
                    _FLAG_RE.match(node.value)):
                continue
            pos = (node.lineno, node.col_offset)
            if pos in def_positions or pos in doc_positions:
                continue
            if node.value not in self.flags:
                findings.append(Finding(
                    "flag-undefined", src.path, node.lineno,
                    f"{node.value!r} is read/exported but never "
                    "registered with define_flag — a typo here fails "
                    "silently as the default value",
                    hint="register it in paddle_tpu/flags.py (or fix "
                         "the name)"))

    # ----------------------------------------------------------- metrics
    def _check_metrics(self, src, findings):
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            cname = (call_name(node) or "").rsplit(".", 1)[-1]
            if cname not in ("counter", "gauge", "histogram"):
                continue
            if not node.args:
                continue
            arg0 = node.args[0]
            if not (isinstance(arg0, ast.Constant) and
                    isinstance(arg0.value, str)):
                continue
            name = arg0.value
            kind = cname
            loc = f"{src.path}:{node.lineno}"
            prior = self.metrics.get(name)
            if prior is not None and prior[0] != kind:
                findings.append(Finding(
                    "metric-duplicate", src.path, node.lineno,
                    f"metric {name!r} registered as {kind} here but as "
                    f"{prior[0]} at {prior[1]} — the registry will "
                    "raise at runtime",
                    hint="rename one of them"))
            elif prior is None:
                self.metrics[name] = (kind, loc)
            if not _NAME_RE.match(name):
                findings.append(Finding(
                    "metric-name", src.path, node.lineno,
                    f"metric name {name!r} is not snake_case "
                    "([a-z][a-z0-9_]*)",
                    hint="prometheus-conventional lowercase snake_case"))
                continue
            if not name.startswith(METRIC_PREFIXES):
                findings.append(Finding(
                    "metric-name", src.path, node.lineno,
                    f"metric {name!r} lacks a subsystem prefix "
                    f"(one of {', '.join(METRIC_PREFIXES)})",
                    hint="prefix it with its owning subsystem"))
            self._check_suffix(src, findings, node, name, kind)

    def _check_suffix(self, src, findings, node, name, kind):
        if kind == "counter" and not name.endswith("_total"):
            findings.append(Finding(
                "metric-suffix", src.path, node.lineno,
                f"counter {name!r} must end in `_total`",
                hint="prometheus counters carry the _total suffix"))
        elif kind == "histogram" and \
                not name.endswith(_HISTO_SUFFIXES):
            findings.append(Finding(
                "metric-suffix", src.path, node.lineno,
                f"histogram {name!r} must end in a unit suffix "
                "(`_seconds` or `_bytes`)",
                hint="name the unit; dashboards and recording rules "
                     "key off it"))
        elif kind == "gauge" and name.endswith("_total"):
            findings.append(Finding(
                "metric-suffix", src.path, node.lineno,
                f"gauge {name!r} must not end in `_total` — that "
                "suffix promises a monotonic counter to rate()/"
                "increase() users",
                hint="drop the suffix or use `_count`/a capacity name"))

    # ------------------------------------------------- label cardinality
    def _check_label_taint(self, src, findings):
        """Flag ``.labels(x)`` where ``x`` is a request/header-derived
        string that never passed through a bounding call."""
        scopes = [src.tree] + [
            n for n in ast.walk(src.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            tainted: set[str] = set()
            for stmt in _flat_statements(getattr(scope, "body", [])):
                for node in _stmt_exprs(stmt):
                    if not (isinstance(node, ast.Call) and
                            isinstance(node.func, ast.Attribute) and
                            node.func.attr == "labels"):
                        continue
                    args = list(node.args) + \
                        [kw.value for kw in node.keywords]
                    if any(_label_tainted(a, tainted) for a in args):
                        findings.append(Finding(
                            "metric-unbounded-label", src.path,
                            node.lineno,
                            "metric label fed from a request/header-"
                            "derived string — every distinct value "
                            "allocates a label child forever "
                            "(unbounded cardinality)",
                            hint="route the value through a bounding "
                                 "map (an LRU table / canonicalizer) "
                                 "before .labels()"))
                # assignments update taint AFTER this statement's
                # .labels sites were judged with the prior state
                target = None
                if isinstance(stmt, ast.Assign) and \
                        len(stmt.targets) == 1 and \
                        isinstance(stmt.targets[0], ast.Name):
                    target = stmt.targets[0].id
                elif isinstance(stmt, ast.AnnAssign) and \
                        isinstance(stmt.target, ast.Name) and \
                        stmt.value is not None:
                    target = stmt.target.id
                if target is not None:
                    if _label_tainted(stmt.value, tainted):
                        tainted.add(target)
                    else:       # re-binding to a clean value sanitizes
                        tainted.discard(target)


# string methods that pass a tainted value through unchanged (still the
# caller-controlled string, just cosmetically normalized)
_PASSTHROUGH = ("strip", "lstrip", "rstrip", "lower", "upper",
                "title", "casefold")


def _label_tainted(node, tainted: set) -> bool:
    """True when ``node`` evaluates to a request/header-derived string
    that no bounding call has been applied to."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Subscript):        # req.headers["X-Tenant"]
        return isinstance(node.value, ast.Attribute) and \
            node.value.attr == "headers"
    if isinstance(node, ast.BoolOp):           # hdr or "anon": still hdr
        return any(_label_tainted(v, tainted) for v in node.values)
    if isinstance(node, ast.IfExp):
        return _label_tainted(node.body, tainted) or \
            _label_tainted(node.orelse, tainted)
    if isinstance(node, ast.BinOp):            # "t:" + hdr, hdr % x
        return _label_tainted(node.left, tainted) or \
            _label_tainted(node.right, tainted)
    if isinstance(node, ast.JoinedStr):        # f"tenant:{hdr}"
        return any(_label_tainted(v.value, tainted)
                   for v in node.values
                   if isinstance(v, ast.FormattedValue))
    if isinstance(node, ast.Call):
        cname = call_name(node) or ""
        if cname.endswith("headers.get"):      # self.headers.get(...)
            return True
        tail = cname.rsplit(".", 1)[-1]
        if tail == "str" and node.args:
            return _label_tainted(node.args[0], tainted)
        if tail in _PASSTHROUGH and isinstance(node.func, ast.Attribute):
            return _label_tainted(node.func.value, tainted)
        return False    # any other call bounds the value (table lookup)
    return False


def _flat_statements(body) -> list:
    """Statements of a scope in source order, descending into control
    flow but never into nested def/class bodies (their own scopes)."""
    out = []
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        out.append(stmt)
        for field in ("body", "orelse", "finalbody"):
            out.extend(_flat_statements(getattr(stmt, field, [])))
        for handler in getattr(stmt, "handlers", []):
            out.extend(_flat_statements(handler.body))
    return out


def _stmt_exprs(stmt):
    """Every expression node belonging to ``stmt`` itself (nested
    statements are visited on their own _flat_statements turn)."""
    for child in ast.iter_child_nodes(stmt):
        if isinstance(child, (ast.stmt, ast.excepthandler)) or \
                type(child).__name__ == "match_case":
            continue
        yield from ast.walk(child)


def _is_test_path(path: str) -> bool:
    parts = path.replace("\\", "/").split("/")
    if "lint_fixtures" in parts:    # linter's own fixtures: full checks
        return False
    return "tests" in parts or parts[-1].startswith("test_")


def _docstring_positions(tree) -> set:
    out = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = getattr(node, "body", [])
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                c = body[0].value
                out.add((c.lineno, c.col_offset))
    return out
