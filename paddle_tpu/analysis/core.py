"""Shared infrastructure for the repo-native static analyzers.

Everything here is stdlib-only and pure-AST: a :class:`Finding` record
(rule id, severity, location, message, fix hint), per-file source
loading with inline ``# tpu-lint: disable=RULE`` suppressions, and the
small AST helpers (dotted-name resolution, expression rendering) every
analyzer shares.  Analyzers never import the code they check — a file
that would crash on import (missing accelerator, heavy deps) still
lints fine.
"""
from __future__ import annotations

import ast
import hashlib
import re
from dataclasses import dataclass, field

__all__ = ["Finding", "SourceFile", "dotted_name", "expr_text",
           "call_name", "SEVERITIES"]

SEVERITIES = ("error", "warning")

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*disable=([A-Za-z0-9_\-]+(?:\s*,\s*[A-Za-z0-9_\-]+)*)")


@dataclass
class Finding:
    """One rule violation at one source location."""

    rule: str
    path: str               # repo-relative, posix separators
    line: int
    message: str
    severity: str = "error"
    hint: str = ""
    col: int = 0

    @property
    def location(self) -> str:
        return f"{self.path}:{self.line}"

    @property
    def fingerprint(self) -> str:
        """Stable identity for baseline matching.  Deliberately excludes
        the line number — adding code above a known finding must not
        turn it into a "new" one."""
        raw = f"{self.rule}|{self.path}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "severity": self.severity, "message": self.message,
                "hint": self.hint, "fingerprint": self.fingerprint}

    def render(self) -> str:
        text = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            text += f"  (fix: {self.hint})"
        return text


@dataclass
class SourceFile:
    """A parsed source file plus its suppression map."""

    path: str               # repo-relative display path
    text: str
    tree: ast.Module
    # line -> set of rule ids suppressed on that line ("all" wildcard)
    suppressions: dict[int, set] = field(default_factory=dict)

    @classmethod
    def load(cls, abspath: str, relpath: str) -> "SourceFile":
        with open(abspath, encoding="utf-8") as f:
            text = f.read()
        tree = ast.parse(text, filename=relpath)
        return cls(relpath, text, tree, _suppression_map(text))

    def suppressed(self, rule: str, line: int) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and ("all" in rules or rule in rules)

    def filter(self, findings: list[Finding]) -> list[Finding]:
        return [f for f in findings
                if not self.suppressed(f.rule, f.line)]


def _suppression_map(text: str) -> dict[int, set]:
    """``# tpu-lint: disable=rule-a,rule-b`` suppresses its own line;
    on a standalone comment line it suppresses the next line instead
    (so a suppression can sit above a long statement)."""
    out: dict[int, set] = {}
    for i, raw in enumerate(text.splitlines(), start=1):
        m = _SUPPRESS_RE.search(raw)
        if not m:
            continue
        rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
        target = i + 1 if raw.lstrip().startswith("#") else i
        out.setdefault(target, set()).update(rules)
    return out


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> str | None:
    """Dotted name of a call's callee (``jax.jit``, ``self.fn``...)."""
    return dotted_name(call.func)


def expr_text(node: ast.AST) -> str:
    """Canonical text of an expression — used to compare 'the same
    buffer' across statements (``self.kpool`` == ``self.kpool``)."""
    try:
        return ast.unparse(node)
    except Exception:               # pragma: no cover - defensive
        return ast.dump(node)
