"""Op-level summary statistics for the profiler.

Reference analog: python/paddle/profiler/profiler_statistic.py — the
SortedKeys enum, per-op EventSummary aggregation, and the formatted
"Operator Summary" table `Profiler.summary()` prints.  The reference
builds these tables from the collected trace tree; here the collector
sits directly on the eager dispatch path (ops/registry.apply_op) and on
RecordEvent user spans, which is where host-side op time is observable
in this runtime (jit-compiled programs are ONE op to the host — their
interior is XLA's domain and is profiled with the device tracer,
jax.profiler; see profiler.py).

While collection is enabled each dispatched op is synchronized
(block_until_ready) before its span closes, so the recorded time is the
op's actual execution time, not its async-dispatch time — the same
semantic the reference gets from CUDA event synchronization in its op
summary.
"""
from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field

__all__ = ["SortedKeys", "EventSummary", "enable_collection",
           "disable_collection", "collection_enabled", "record_span",
           "reset", "op_summary", "gen_summary_table"]


class SortedKeys(enum.IntEnum):
    """Sort orders for the op summary table (reference
    profiler_statistic.py SortedKeys; the CPU/GPU pairs collapse — one
    synchronized host span per op covers the device work).  IntEnum so
    reference-style integer keys keep working."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7


@dataclass
class EventSummary:
    """Aggregate of every span with one name (reference
    profiler_statistic.EventSummary.ItemBase)."""
    name: str
    kind: str = "op"            # "op" (dispatch) | "user" (RecordEvent)
    call: int = 0
    total: float = 0.0          # seconds
    max: float = 0.0
    min: float = field(default=float("inf"))

    def add(self, dt: float):
        self.call += 1
        self.total += dt
        self.max = max(self.max, dt)
        self.min = min(self.min, dt)

    @property
    def avg(self) -> float:
        return self.total / self.call if self.call else 0.0


ENABLED = False
_STATS: dict[tuple[str, str], EventSummary] = {}
# RecordEvent spans close from whatever thread ran them — under the
# threaded serving server that means concurrent record_span calls, so
# the aggregate map needs a lock (EventSummary.add is a read-modify-
# write of three fields).
_STATS_LOCK = threading.Lock()


def enable_collection(on: bool = True):
    global ENABLED
    ENABLED = bool(on)


def disable_collection():
    enable_collection(False)


def collection_enabled() -> bool:
    return ENABLED


def reset():
    with _STATS_LOCK:
        _STATS.clear()


def record_span(name: str, dt: float, kind: str = "op"):
    key = (kind, name)
    with _STATS_LOCK:
        s = _STATS.get(key)
        if s is None:
            s = _STATS[key] = EventSummary(name=name, kind=kind)
        s.add(dt)


def op_summary() -> list[EventSummary]:
    with _STATS_LOCK:
        return list(_STATS.values())


_UNITS = {"s": 1.0, "ms": 1e3, "us": 1e6, "ns": 1e9}

_SORT_ATTR = {
    SortedKeys.CPUTotal: "total", SortedKeys.GPUTotal: "total",
    SortedKeys.CPUAvg: "avg", SortedKeys.GPUAvg: "avg",
    SortedKeys.CPUMax: "max", SortedKeys.GPUMax: "max",
    SortedKeys.CPUMin: "min", SortedKeys.GPUMin: "min",
}


def gen_summary_table(sorted_by=SortedKeys.CPUTotal, time_unit="ms",
                      op_detail=True) -> str:
    """Render the collected spans as the reference-shaped summary table
    (profiler_statistic._build_table's Operator Summary section)."""
    if time_unit not in _UNITS:
        raise ValueError(f"time_unit must be one of {sorted(_UNITS)}, "
                         f"got {time_unit!r}")
    try:
        sorted_by = SortedKeys(sorted_by)
    except ValueError:
        raise TypeError(f"sorted_by must be a SortedKeys, got {sorted_by!r}")
    items = sorted(op_summary(), key=lambda s: getattr(s, _SORT_ATTR[
        sorted_by]), reverse=sorted_by not in (SortedKeys.CPUMin,
                                               SortedKeys.GPUMin))
    mult = _UNITS[time_unit]

    name_w = max([len(s.name) + 7 for s in items] + [12]) + 2
    head = (f"{'Name':<{name_w}}{'Calls':>8}{'Total(' + time_unit + ')':>14}"
            f"{'Avg(' + time_unit + ')':>12}{'Max(' + time_unit + ')':>12}"
            f"{'Min(' + time_unit + ')':>12}{'Ratio(%)':>10}")
    bar = "-" * len(head)
    lines = []
    # two sections, reference-style: Operator Summary for dispatched ops,
    # UserDefined Summary for RecordEvent spans (which NEST ops — merging
    # them would double-count and bury the op ranking)
    for kind, title in (("op", "Operator Summary"),
                        ("user", "UserDefined Summary")):
        sect = [s for s in items if s.kind == kind]
        if not sect or (kind == "user" and not op_detail):
            continue
        grand = sum(s.total for s in sect) or 1.0
        lines += [title, bar, head, bar]
        for s in sect:
            nm = s.name if s.kind == "op" else f"{s.name} (user)"
            lines.append(
                f"{nm:<{name_w}}{s.call:>8}{s.total * mult:>14.4f}"
                f"{s.avg * mult:>12.4f}{s.max * mult:>12.4f}"
                f"{(0.0 if s.min == float('inf') else s.min) * mult:>12.4f}"
                f"{100.0 * s.total / grand:>10.2f}")
        lines.append(bar)
    return "\n".join(lines)
