"""Profiler over jax.profiler (reference: python/paddle/profiler/profiler.py
— Profiler:358 with scheduler states:89; CUPTI tracers collapse into XLA's
own TPU trace; export is TensorBoard/perfetto instead of chrome-trace JSON,
with the same Python API shape).
"""
from __future__ import annotations

import enum
import os
import tempfile
import threading
import time

import jax

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result", "enable_host_tracing",
           "export_host_trace", "host_trace_event_count"]


_host_tracing_requested = False


def _native():
    try:
        from ..core.native import load
        return load()
    except Exception:  # pragma: no cover
        return None


def enable_host_tracing(on: bool = True) -> bool:
    global _host_tracing_requested
    _host_tracing_requested = bool(on)
    return _enable_host_tracing_impl(on)


def _enable_host_tracing_impl(on: bool) -> bool:
    """Turn on the native C++ host tracer (csrc/trace.cc — analog of the
    reference HostTracer, event_tracing.h).  RecordEvent spans are then
    recorded natively in addition to the jax trace annotation.  Returns
    whether the native tracer is available."""
    lib = _native()
    if lib is None:
        return False
    lib.pt_trace_enable(1 if on else 0)
    return True


def export_host_trace(path: str) -> bool:
    """Write collected host spans as chrome://tracing JSON (analog of
    chrometracing_logger.cc).  Three sources merge onto one timeline —
    the native tracer, the metrics registry's sampled counters, and the
    observability span ring (request/engine spans from the serving
    stack) all stamp CLOCK_MONOTONIC (steady_clock / perf_counter).
    Span events carry the real OS tid of the thread that ran them plus
    "M"-phase thread_name metadata, so the engine worker, HTTP handler,
    and router threads render as separate named rows."""
    from .. import observability as _obs
    pid = os.getpid()
    extras = _obs.chrome_counter_events(pid)
    extras += _obs.tracer().chrome_events(pid)
    lib = _native()
    if lib is None:
        if not extras:
            return False
        import json
        with open(path, "w") as f:
            json.dump({"traceEvents": extras}, f)
        return True
    ok = lib.pt_trace_export(path.encode(), pid) == 0
    if ok and extras:
        import json
        try:
            with open(path) as f:
                doc = json.load(f)
            doc.setdefault("traceEvents", []).extend(extras)
            with open(path, "w") as f:
                json.dump(doc, f)
        except (OSError, ValueError):    # leave the native export as-is
            pass
    return ok


def host_trace_event_count() -> int:
    lib = _native()
    return 0 if lib is None else int(lib.pt_trace_count())


class ProfilerTarget(enum.Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(enum.Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


def make_scheduler(*, closed, ready, record, repeat=0, skip_first=0):
    """State machine over step numbers (reference profiler.py:89)."""
    period = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    """on_trace_ready callback: point the trace dir (perfetto/tensorboard
    format on TPU) at dir_name."""
    def handler(prof):
        prof._export_dir = dir_name
    return handler


def load_profiler_result(path):
    raise NotImplementedError(
        "TPU traces are perfetto/tensorboard artifacts; open with "
        "tensorboard --logdir or ui.perfetto.dev")


class Profiler:
    """paddle.profiler.Profiler-shaped wrapper over jax.profiler.

    with Profiler(targets=[ProfilerTarget.TPU]) as p:
        for batch in loader:
            train_step(batch)
            p.step()
    """

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None,
                 with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(closed=0, ready=0, record=scheduler[1] or 1,
                           skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._export_dir = None
        self._step = 0
        self._recording = False
        self._step_times = []
        self._t_last = None
        self._stats_on = False      # whether THIS profiler enabled the
                                    # global op-stats collection

    # ------------------------------------------------------------- control
    def start(self):
        from . import statistic
        self._t_last = time.perf_counter()
        if self._timer_only:
            return
        state = self._state()
        if state in (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN):
            # reset only when THIS profiler will actually record — a
            # CLOSED-state start() must not wipe the global op-stats a
            # concurrently recording profiler is accumulating (mirrors
            # the _stats_on guard in stop())
            statistic.reset()
            self._start_trace()
            statistic.enable_collection()
            self._stats_on = True

    def stop(self):
        if self._stats_on:
            # only the profiler that ENABLED collection may disable it —
            # a timer-only or never-recording profiler must not flip the
            # global flag out from under a recording one
            from . import statistic
            statistic.disable_collection()
            self._stats_on = False
        if self._recording:
            self._stop_trace()
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        from . import statistic
        now = time.perf_counter()
        if self._t_last is not None:
            self._step_times.append(now - self._t_last)
        self._t_last = now
        prev = self._state()
        self._step += 1
        cur = self._state()
        if self._timer_only:
            return
        if prev != cur:
            if cur in (ProfilerState.RECORD,
                       ProfilerState.RECORD_AND_RETURN) and \
                    not self._recording:
                # scheduler-delayed recording starts HERE, not in
                # start(): reset now so a previous profiler's op-stats
                # don't merge into this run's summary
                statistic.reset()
                self._start_trace()
                statistic.enable_collection()
                self._stats_on = True
            elif cur == ProfilerState.CLOSED and self._recording:
                self._stop_trace()
                if self._stats_on:
                    statistic.disable_collection()
                    self._stats_on = False

    def _state(self):
        if self._scheduler is None:
            return ProfilerState.RECORD
        return self._scheduler(self._step)

    def _start_trace(self):
        from .. import observability as _obs
        out = self._export_dir or os.path.join(tempfile.gettempdir(),
                                               "paddle_tpu_trace")
        try:
            jax.profiler.start_trace(out)
            self._recording = True
        except Exception:
            self._recording = False
        # counter tracks sample over the same recording window
        _obs.enable_event_sampling(self._recording)

    def _stop_trace(self):
        from .. import observability as _obs
        try:
            jax.profiler.stop_trace()
        finally:
            self._recording = False
            _obs.enable_event_sampling(False)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()

    # ------------------------------------------------------------- summary
    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms"):
        """Print the overview + op-level summary table (reference
        profiler_statistic._build_table).  `sorted_by` is a
        statistic.SortedKeys; returns the table string too, so callers
        can post-process (the reference prints only)."""
        from . import statistic
        out = []
        if self._step_times:
            import numpy as np
            ts = np.asarray(self._step_times) * 1e3
            out.append(f"steps: {len(ts)}  avg: {ts.mean():.3f}ms  "
                       f"min: {ts.min():.3f}ms  max: {ts.max():.3f}ms")
        if statistic.op_summary():
            out.append(statistic.gen_summary_table(
                sorted_by=sorted_by or statistic.SortedKeys.CPUTotal,
                time_unit=time_unit, op_detail=op_detail))
        text = "\n".join(out) if out else "no profiled steps"
        print(text)
        return text


class RecordEvent:
    """Named host span visible in the trace (reference
    phi::RecordEvent / event_tracing.h) — maps to
    jax.profiler.TraceAnnotation.

    One instance may be shared across threads (module-level RecordEvents
    wrapping collectives under the threaded serving server), so all
    per-use state — start time, the TraceAnnotation, the native-stack
    pushed flag — lives in a threading.local; concurrent begin()/end()
    pairs on different threads never clobber each other."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._tls = threading.local()

    def begin(self):
        tls = self._tls
        tls.t0 = time.perf_counter()
        tls.pushed = False
        # only touch (and possibly build) the native lib if host tracing was
        # ever requested — keeps the default path free of g++ invocations
        if _host_tracing_requested:
            lib = _native()
            if lib is not None and lib.pt_trace_enabled():
                lib.pt_trace_begin(self.name.encode())
                tls.pushed = True
        tls.ann = jax.profiler.TraceAnnotation(self.name)
        tls.ann.__enter__()

    def end(self):
        tls = self._tls
        t0 = getattr(tls, "t0", None)
        if t0 is None:          # end() without begin() on this thread
            return
        tls.t0 = None
        tls.ann.__exit__(None, None, None)
        from . import statistic
        if statistic.ENABLED:
            dt = time.perf_counter() - t0
            statistic.record_span(self.name, dt, "user")
        if tls.pushed:
            # pop regardless of the current enabled state so the native
            # thread-local span stack stays balanced
            lib = _native()
            if lib is not None:
                lib.pt_trace_end()
            tls.pushed = False

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()


# FLAGS_host_trace=1 in the environment turns the native host tracer on
# at import (the reference's FLAGS_enable_host_event_recorder_hook env
# seeding) — failures (no g++ in a stripped container) stay soft.
def _seed_host_tracing_from_flags():
    from ..flags import FLAGS
    if FLAGS.get("FLAGS_host_trace"):
        try:
            enable_host_tracing(True)
        except Exception:   # pragma: no cover
            pass


_seed_host_tracing_from_flags()
