"""Throughput timer (reference: python/paddle/profiler/timer.py Benchmark)."""
from __future__ import annotations

import time

__all__ = ["Benchmark", "benchmark"]


class Benchmark:
    def __init__(self):
        self.reset()

    def reset(self):
        self._t0 = None
        self._steps = 0
        self._samples = 0
        self._reader_cost = 0.0
        self._batch_start = None

    def begin(self):
        self.reset()
        self._t0 = time.perf_counter()
        self._batch_start = self._t0

    def before_reader(self):
        self._reader_t0 = time.perf_counter()

    def after_reader(self):
        self._reader_cost += time.perf_counter() - self._reader_t0

    def after_step(self, num_samples=1):
        self._steps += 1
        self._samples += num_samples

    step = after_step

    def end(self):
        self._elapsed = time.perf_counter() - self._t0

    @property
    def ips(self):
        el = getattr(self, "_elapsed", None) or \
            (time.perf_counter() - self._t0)
        return self._samples / el if el else 0.0

    def report(self):
        return {"steps": self._steps, "samples": self._samples,
                "ips": self.ips, "reader_cost": self._reader_cost}


_global_benchmark = Benchmark()


def benchmark():
    return _global_benchmark
