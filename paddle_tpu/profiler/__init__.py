"""paddle.profiler (reference: python/paddle/profiler — Profiler:358,
scheduler states:89, export_chrome_tracing:227, timer.py Benchmark)."""
from .profiler import (  # noqa: F401
    Profiler, ProfilerTarget, ProfilerState, RecordEvent, make_scheduler,
    export_chrome_tracing, load_profiler_result, enable_host_tracing,
    export_host_trace, host_trace_event_count)
from .timer import Benchmark, benchmark  # noqa: F401
