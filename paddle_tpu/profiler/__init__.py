"""paddle.profiler (reference: python/paddle/profiler — Profiler:358,
scheduler states:89, export_chrome_tracing:227, timer.py Benchmark)."""
from .profiler import (  # noqa: F401
    Profiler, ProfilerTarget, ProfilerState, RecordEvent, make_scheduler,
    export_chrome_tracing, load_profiler_result, enable_host_tracing,
    export_host_trace, host_trace_event_count)
from .statistic import SortedKeys, EventSummary  # noqa: F401
from .timer import Benchmark, benchmark  # noqa: F401

__all__ = ["Profiler", "ProfilerTarget", "ProfilerState", "RecordEvent",
           "make_scheduler", "export_chrome_tracing",
           "load_profiler_result", "Benchmark", "benchmark", "SortedKeys",
           "SummaryView", "export_protobuf"]


class SummaryView:
    """Summary view kinds (reference profiler/profiler.py SummaryView
    enum)."""
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6
    MemoryManipulationView = 7
    UDFView = 8


def export_protobuf(dir_name=None, worker_name=None):
    """Profiler export callback (reference profiler/profiler.py
    export_protobuf).  The jax profiler writes TensorBoard/perfetto
    protobufs natively; this returns the matching on_trace_ready hook."""
    def handle(prof):
        import jax
        out = dir_name or "./profiler_log"
        try:
            jax.profiler.save_device_memory_profile(
                f"{out}/memory.pprof")
        except Exception:
            pass
        return out
    return handle



