"""Optimizer base (reference: python/paddle/optimizer/optimizer.py, 2052 LoC).

State (accumulators, master weights) is a dict of jax arrays keyed by
parameter name — a pytree, so a whole optimizer.step can run inside one
jitted update when driven through jit/functional.py.  Updates compute in
fp32 (master weights for low-precision params, reference `_master_weights`
optimizer.py:317) and write back in the param dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..autograd import no_grad

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        from .lr import LRScheduler
        import paddle_tpu
        if parameters is None and not paddle_tpu.in_dynamic_mode():
            parameters = []       # static mode: filled by minimize()
        if parameters is None:
            raise ValueError(
                "parameters is required in dygraph mode (pass model.parameters())")
        self._parameter_list = list(parameters)
        if not self._parameter_list and paddle_tpu.in_dynamic_mode():
            raise ValueError("optimizer got an empty parameter list")
        self._lr = learning_rate
        self._lr_scheduler = learning_rate if isinstance(
            learning_rate, LRScheduler) else None
        self._l1_coeff = 0.0
        if isinstance(weight_decay, float):
            self._coeff = weight_decay
        elif weight_decay is None:
            self._coeff = 0.0
        else:  # L1Decay/L2Decay-like object with a coeff
            coeff = float(getattr(weight_decay, "_coeff",
                                  getattr(weight_decay, "coeff", 0.0)))
            if type(weight_decay).__name__ == "L1Decay":
                self._l1_coeff = coeff
                self._coeff = 0.0
            else:
                self._coeff = coeff
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._accumulators: dict[str, dict[str, jnp.ndarray]] = {}
        self._master_weights: dict[str, jnp.ndarray] = {}
        self._step_count = 0
        self._name = name

    # ------------------------------------------------------------ lr
    def get_lr(self):
        if self._lr_scheduler is not None:
            return self._lr_scheduler()
        return float(self._lr)

    def set_lr(self, value):
        self._lr = float(value)
        self._lr_scheduler = None

    # ------------------------------------------------------------ state
    def _param_key(self, p):
        return p.name

    def _get_master(self, p):
        key = self._param_key(p)
        if not self._multi_precision or p._data.dtype == jnp.float32:
            return None
        if key not in self._master_weights:
            self._master_weights[key] = p._data.astype(jnp.float32)
        return self._master_weights[key]

    def _acc(self, p, name, init=None):
        key = self._param_key(p)
        slot = self._accumulators.setdefault(key, {})
        if name not in slot:
            slot[name] = init if init is not None else \
                jnp.zeros(p._data.shape, jnp.float32)
        return slot[name]

    def _set_acc(self, p, name, value):
        self._accumulators[self._param_key(p)][name] = value

    # ------------------------------------------------------------ step
    @no_grad()
    def step(self):
        params_grads = []
        for p in self._parameter_list:
            if p.stop_gradient or p._grad is None:
                continue
            params_grads.append((p, p._grad))
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        gt = getattr(self, "_grad_transform", None)
        if gt is None and params_grads and self._try_fused_step(
                params_grads, lr):
            self._step_count += 1
            return
        from ..framework.selected_rows import RowSparseGrad
        for p, g in params_grads:
            if g is None:
                continue
            g32 = g.astype(jnp.float32)
            if isinstance(g32, RowSparseGrad) and gt is None \
                    and not self._l1_coeff:
                self._update_param_rowsparse(p, g32, lr)
                continue
            if isinstance(g32, RowSparseGrad):
                # sharded-grad transforms / L1 operate on dense math
                g32 = g32.to_dense()
            if gt is not None:
                # sharding-stage>=2: reduce-scatter semantics — the grad
                # becomes dp-sharded so update math runs on shards only
                g32 = gt(g32)
            if self._l1_coeff:  # L1 regularization: grad += c * sign(param)
                g32 = g32 + self._l1_coeff * jnp.sign(self._param_f32(p))
            self._update_param(p, g32, lr)
        self._step_count += 1

    # ----------------------------------------------------- fused eager step
    # Eager per-param updates dispatch 2-5 device ops per parameter; the
    # reference fuses them (phi multi_tensor_adam / fused kernels).  The
    # TPU analog: replay the subclass's _update_param math for ALL params
    # under one cached jit, with lr/step passed as traced scalars so
    # schedulers and Adam bias correction stay step-accurate.
    def _try_fused_step(self, params_grads, lr):
        import jax

        if getattr(self, "_fused_step_broken", False):
            return False
        if "_acc" in self.__dict__ or hasattr(self, "_shard_state_fn") \
                or getattr(self, "_param_restore", None) is not None:
            # sharded-state optimizers (shard_optimizer stages) place
            # accumulators with device_put; inside a jit that placement
            # becomes advisory and XLA replicates — keep the eager loop
            return False
        from ..framework.selected_rows import RowSparseGrad
        if any(g is None or isinstance(g, RowSparseGrad)
               for _, g in params_grads):
            return False
        ps = [p for p, _ in params_grads]
        gs = [g for _, g in params_grads]
        if any(isinstance(x._data if hasattr(x, "_data") else x,
                          jax.core.Tracer) for x in ps + gs):
            return False          # traced context (train_step): legacy path
        keys = [self._param_key(p) for p in ps]
        accs_in = {k: dict(self._accumulators.get(k, {})) for k in keys}
        masters_in = {k: self._master_weights[k] for k in keys
                      if k in self._master_weights}
        sig = (tuple((str(p._data.dtype), p._data.shape) for p in ps),
               tuple((str(g.dtype), g.shape) for g in gs),
               tuple((k, tuple(sorted(v))) for k, v in accs_in.items()),
               tuple(sorted(masters_in)))
        cache = self.__dict__.setdefault("_fused_step_cache", {})
        fn = cache.get(sig)
        if fn is None:
            opt = self

            def run(pvals, gvals, accs, masters, lr_arr, prev_steps):
                saved = ([p._data for p in ps], opt._accumulators,
                         opt._master_weights, opt._step_count)
                try:
                    for p, pv in zip(ps, pvals):
                        p._data = pv
                    opt._accumulators = {k: dict(v)
                                         for k, v in accs.items()}
                    opt._master_weights = dict(masters)
                    opt._step_count = prev_steps
                    for p, g in zip(ps, gvals):
                        g32 = g.astype(jnp.float32)
                        if opt._l1_coeff:
                            g32 = g32 + opt._l1_coeff * jnp.sign(
                                opt._param_f32(p))
                        opt._update_param(p, g32, lr_arr)
                    new_p = [p._data for p in ps]
                    new_accs = {k: dict(opt._accumulators.get(k, {}))
                                for k in keys}
                    new_masters = {k: opt._master_weights[k] for k in keys
                                   if k in opt._master_weights}
                    return new_p, new_accs, new_masters
                finally:
                    (pd, opt._accumulators, opt._master_weights,
                     opt._step_count) = saved[0], saved[1], saved[2], \
                        saved[3]
                    for p, pv in zip(ps, pd):
                        p._data = pv

            fn = jax.jit(run)
        try:
            import numpy as _np

            # numpy scalars, NOT jnp: jnp.float32(lr) is an eager
            # device_put dispatch per step; as np scalars the transfer
            # rides the jitted call itself
            new_p, new_accs, new_masters = fn(
                [p._data for p in ps], gs, accs_in, masters_in,
                _np.float32(lr), _np.int32(self._step_count))
        except Exception:
            # subclass math not traceable (host-side control flow, e.g.
            # line searches): permanently take the legacy loop
            self._fused_step_broken = True
            return False
        cache[sig] = fn
        for p, nv in zip(ps, new_p):
            key = self._param_key(p)
            if key in new_masters:
                self._master_weights[key] = new_masters[key]
            p._data = nv
        for k, v in new_accs.items():
            if v:
                self._accumulators[k] = v
        return True

    def _update_param(self, p, grad_f32, lr):
        raise NotImplementedError

    def _update_param_rowsparse(self, p, g, lr):
        """Apply a RowSparseGrad.  Base behavior: densify (with a one-time
        note) — SGD and lazy Adam/AdamW override with true row updates
        (reference: sgd SelectedRows kernel + adam lazy_mode,
        paddle/phi/kernels/selected_rows/)."""
        if not getattr(type(self), "_rs_densify_warned", False):
            import logging
            logging.getLogger("paddle_tpu").info(
                "%s has no row-sparse update; densifying embedding grad "
                "(use SGD or Adam/AdamW(lazy_mode=True) for row updates)",
                type(self).__name__)
            type(self)._rs_densify_warned = True
        self._update_param(p, g.to_dense(), lr)

    def _write_back(self, p, new_f32):
        key = self._param_key(p)
        if key in self._master_weights:
            self._master_weights[key] = new_f32
        out = new_f32.astype(p._data.dtype)
        restore = getattr(self, "_param_restore", None)
        if restore is not None:
            # sharding-stage 2: updated shards gather back to the param's
            # own layout (replicated); stage 3 params are sharded so this
            # is a no-op placement
            out = restore(p, out)
        p._data = out

    def _param_f32(self, p):
        master = self._get_master(p)
        return master if master is not None else p._data.astype(jnp.float32)

    def clear_grad(self, set_to_zero=True):
        for p in self._parameter_list:
            p.clear_grad(set_to_zero=False)

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import graph as _sgraph
        if isinstance(loss, _sgraph.Variable):
            # static build: record a train op; Executor.run computes the
            # grads and calls step() (reference: appended optimizer ops)
            prog = loss.program
            prog.train_ops.append((self, loss))
            prog.version += 1
            if not self._parameter_list:
                self._parameter_list = [
                    p for p in prog.all_parameters() if not p.stop_gradient]
            return None, None
        loss.backward()
        self.step()
        self.clear_grad()

    # ------------------------------------------------------------ ckpt
    def state_dict(self):
        state = {}
        for pkey, slots in self._accumulators.items():
            for sname, arr in slots.items():
                state[f"{pkey}.{sname}"] = Tensor(arr)
        for pkey, arr in self._master_weights.items():
            state[f"{pkey}.master_weight"] = Tensor(arr)
        if self._lr_scheduler is not None:
            state["LR_Scheduler"] = self._lr_scheduler.state_dict()
        state["@step"] = self._step_count
        return state

    def set_state_dict(self, state):
        for key, val in state.items():
            if key == "LR_Scheduler":
                if self._lr_scheduler is not None:
                    self._lr_scheduler.set_state_dict(val)
                continue
            if key == "@step":
                self._step_count = int(val)
                continue
            pkey, sname = key.rsplit(".", 1)
            arr = val._data if isinstance(val, Tensor) else jnp.asarray(val)
            if sname == "master_weight":
                self._master_weights[pkey] = arr
            else:
                self._accumulators.setdefault(pkey, {})[sname] = arr

    # ------------------------------------------------- functional bridge
    def opt_state(self):
        """All optimizer state as a pytree of jax arrays (for jit)."""
        return {"acc": {k: dict(v) for k, v in self._accumulators.items()},
                "master": dict(self._master_weights),
                "step": self._step_count}

    def load_opt_state(self, state):
        self._accumulators = {k: dict(v) for k, v in state["acc"].items()}
        self._master_weights = dict(state["master"])
        self._step_count = int(state["step"]) if not hasattr(
            state["step"], "dtype") else state["step"]
