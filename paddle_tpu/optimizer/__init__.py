"""paddle.optimizer namespace."""
from .optimizer import Optimizer
from .optimizers import SGD, Momentum, Adam, AdamW, Adagrad, Adadelta, \
    RMSProp, Lamb
from . import lr

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "lr"]
