"""paddle.optimizer namespace."""
from .optimizer import Optimizer
from .optimizers import SGD, Momentum, Adam, AdamW, Adagrad, Adadelta, \
    RMSProp, Lamb
from .optimizers_extra import Adamax, ASGD, NAdam, RAdam, Rprop, LBFGS
from . import lr

__all__ = ["Optimizer", "SGD", "Momentum", "Adam", "AdamW", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "Adamax", "ASGD", "NAdam",
           "RAdam", "Rprop", "LBFGS", "lr"]
