"""Remaining reference optimizers: Adamax, ASGD, NAdam, RAdam, Rprop, LBFGS.

Math matches the reference phi kernels:
  Adamax  paddle/phi/kernels/impl/adamax_kernel_impl.h:61-69
  NAdam   paddle/phi/kernels/impl/nadam_kernel_impl.h:77-108
  RAdam   paddle/phi/kernels/impl/radam_kernel_impl.h:76-117
  Rprop   paddle/phi/kernels/cpu/rprop_kernel.cc:69-101
  ASGD    paddle/phi/kernels/cpu/asgd_kernel.cc:25-48 (+ python ring buffer
          python/paddle/optimizer/asgd.py:240-320)
  LBFGS   python/paddle/optimizer/lbfgs.py (two-loop recursion + strong Wolfe)
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .optimizer import Optimizer

__all__ = ["Adamax", "ASGD", "NAdam", "RAdam", "Rprop", "LBFGS"]


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        m = self._acc(p, "moment")
        u = self._acc(p, "inf_norm")
        t = self._step_count + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        # reference: inf_norm = max(|g|, beta2*inf_norm + eps)
        u = jnp.maximum(jnp.abs(g), self._beta2 * u + self._epsilon)
        self._set_acc(p, "moment", m)
        self._set_acc(p, "inf_norm", u)
        lr_t = lr / (1 - self._beta1 ** t)
        self._write_back(p, x - lr_t * m / u)


class ASGD(Optimizer):
    """Stochastic Average Gradient (the reference calls it ASGD): keeps the
    last gradient seen at each of ``batch_num`` ring slots and steps with the
    running sum d/min(step, n).

    Memory note: the ring buffer costs ``batch_num`` fp32 copies of EVERY
    parameter on device (mirroring the reference design,
    python/paddle/optimizer/asgd.py:240) — with large ``batch_num`` this
    dwarfs the params themselves; a warning is emitted past 64."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        if batch_num is not None and batch_num > 64:
            import warnings
            warnings.warn(
                f"ASGD allocates batch_num={batch_num} fp32 copies of "
                "every parameter for its gradient ring buffer "
                f"(~{batch_num}x param memory)")
        if batch_num is None or batch_num <= 0:
            raise ValueError("batch_num should be greater than 0")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._n = int(batch_num)

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        d = self._acc(p, "d")
        ys = self._acc(p, "y",
                       jnp.zeros((self._n,) + tuple(p._data.shape),
                                 jnp.float32))
        idx = self._step_count % self._n
        d = d - ys[idx] + g
        ys = ys.at[idx].set(g)
        self._set_acc(p, "d", d)
        self._set_acc(p, "y", ys)
        n_eff = jnp.minimum(self._step_count + 1, self._n)
        self._write_back(p, x - (lr / n_eff) * d)


class NAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._psi = momentum_decay

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        # mu_product carried per-param so each param's schedule is exact
        mu_prod = self._acc(p, "mu_product", jnp.ones((), jnp.float32))
        t = self._step_count + 1
        mu_t = self._beta1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_t1 = self._beta1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = mu_prod * mu_t
        mu_prod_t1 = mu_prod * mu_t1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc(p, "moment1", m)
        self._set_acc(p, "moment2", v)
        self._set_acc(p, "mu_product", mu_prod)
        m_hat = (mu_t1 * m / (1 - mu_prod_t1)
                 + (1 - mu_t) * g / (1 - mu_prod))
        v_hat = v / (1 - self._beta2 ** t)
        self._write_back(p, x - lr * m_hat / (jnp.sqrt(v_hat) + self._epsilon))


class RAdam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc(p, "moment1", m)
        self._set_acc(p, "moment2", v)
        rho_inf = 2.0 / (1.0 - self._beta2) - 1.0
        beta2_t = self._beta2 ** t
        rho_t = rho_inf - 2.0 * t * beta2_t / (1.0 - beta2_t)
        m_hat = m / (1 - self._beta1 ** t)
        # rectified update (reference radam_kernel_impl.h:100); jnp.where so
        # the step count may be a traced value under the jitted train step
        l_t = jnp.sqrt(1.0 - beta2_t) / (jnp.sqrt(v) + self._epsilon)
        safe_rho = jnp.maximum(rho_t, 5.0 + 1e-6)
        r_t = jnp.sqrt((safe_rho - 4) * (safe_rho - 2) * rho_inf /
                       ((rho_inf - 4) * (rho_inf - 2) * safe_rho))
        self._write_back(p, x - jnp.where(rho_t > 5.0,
                                          lr * m_hat * r_t * l_t,
                                          lr * m_hat))


class Rprop(Optimizer):
    def __init__(self, learning_rate=0.001, learning_rate_range=(1e-5, 50),
                 parameters=None, etas=(0.5, 1.2), grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._lr_min, self._lr_max = learning_rate_range
        self._eta_neg, self._eta_pos = etas

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        prev = self._acc(p, "prev")
        lrs = self._acc(p, "learning_rate",
                        jnp.full(p._data.shape, float(lr), jnp.float32))
        sign = g * prev
        eta = jnp.where(sign > 0, self._eta_pos,
                        jnp.where(sign < 0, self._eta_neg, 1.0))
        g = jnp.where(sign < 0, 0.0, g)  # reference zeroes grad on sign flip
        lrs = jnp.clip(lrs * eta, self._lr_min, self._lr_max)
        self._set_acc(p, "prev", g)
        self._set_acc(p, "learning_rate", lrs)
        self._write_back(p, x - jnp.sign(g) * lrs)


class LBFGS(Optimizer):
    """Limited-memory BFGS with optional strong-Wolfe line search.

    Reference python/paddle/optimizer/lbfgs.py: single-tensor flattened
    history, two-loop recursion, ``step(closure)`` API where closure
    re-evaluates the loss (and grads) at trial points.
    """

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-07, tolerance_change=1e-09,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        if max_eval is None:
            max_eval = max_iter * 5 // 4
        self._max_iter = max_iter
        self._max_eval = max_eval
        self._tol_grad = tolerance_grad
        self._tol_change = tolerance_change
        self._history_size = history_size
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("only 'strong_wolfe' is supported")
        if grad_clip is not None:
            raise ValueError(
                "LBFGS does not support grad_clip: the line search needs raw "
                "closure gradients (reference lbfgs.py has no clip path)")
        self._line_search_fn = line_search_fn
        self._state = {"old_sks": [], "old_yks": [], "ro": [],
                       "H_diag": 1.0, "prev_flat_grad": None, "d": None,
                       "t": None, "n_iter": 0, "func_evals": 0}

    def state_dict(self):
        state = super().state_dict()
        st = self._state
        state["@lbfgs"] = {
            "old_sks": [np.asarray(a) for a in st["old_sks"]],
            "old_yks": [np.asarray(a) for a in st["old_yks"]],
            "ro": list(st["ro"]),
            "H_diag": st["H_diag"],
            "prev_flat_grad": None if st["prev_flat_grad"] is None
            else np.asarray(st["prev_flat_grad"]),
            "d": None if st["d"] is None else np.asarray(st["d"]),
            "t": st["t"], "n_iter": st["n_iter"],
            "func_evals": st["func_evals"]}
        return state

    def set_state_dict(self, state):
        state = dict(state)
        lb = state.pop("@lbfgs", None)
        super().set_state_dict(state)
        if lb is not None:
            self._state = {
                "old_sks": [jnp.asarray(a) for a in lb["old_sks"]],
                "old_yks": [jnp.asarray(a) for a in lb["old_yks"]],
                "ro": list(lb["ro"]),
                "H_diag": lb["H_diag"],
                "prev_flat_grad": None if lb["prev_flat_grad"] is None
                else jnp.asarray(lb["prev_flat_grad"]),
                "d": None if lb["d"] is None else jnp.asarray(lb["d"]),
                "t": lb["t"], "n_iter": lb["n_iter"],
                "func_evals": lb["func_evals"]}

    # ---- flat views over the parameter list
    def _gather_flat_grad(self):
        flat = []
        for p in self._parameter_list:
            g = p._grad
            if g is None:
                g = jnp.zeros(p._data.shape, p._data.dtype)
            elif hasattr(g, "_data"):
                g = g._data
            g = jnp.reshape(g, (-1,)).astype(jnp.float32)
            if self._coeff:  # L2 regularization folded into the grad
                g = g + self._coeff * jnp.reshape(
                    p._data, (-1,)).astype(jnp.float32)
            flat.append(g)
        return jnp.concatenate(flat)

    def _add_delta(self, step_size, direction):
        off = 0
        for p in self._parameter_list:
            n = int(np.prod(p._data.shape)) if p._data.shape else 1
            upd = direction[off:off + n].reshape(p._data.shape)
            p._data = (p._data.astype(jnp.float32)
                       + step_size * upd).astype(p._data.dtype)
            off += n

    def _clone_params(self):
        return [p._data for p in self._parameter_list]

    def _restore_params(self, snapshot):
        for p, d in zip(self._parameter_list, snapshot):
            p._data = d

    def step(self, closure):
        closure_fn = closure
        loss = float(closure_fn())
        self._state["func_evals"] += 1
        flat_grad = self._gather_flat_grad()
        if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
            return loss

        st = self._state
        lr = self.get_lr()
        current_evals = 1
        n_iter = 0
        while n_iter < self._max_iter:
            n_iter += 1
            st["n_iter"] += 1
            # --- direction via two-loop recursion
            if st["n_iter"] == 1:
                d = -flat_grad
                st["old_sks"], st["old_yks"], st["ro"] = [], [], []
                st["H_diag"] = 1.0
            else:
                y = flat_grad - st["prev_flat_grad"]
                s = st["d"] * st["t"]
                ys = float(jnp.dot(y, s))
                if ys > 1e-10:
                    if len(st["old_sks"]) == self._history_size:
                        st["old_sks"].pop(0)
                        st["old_yks"].pop(0)
                        st["ro"].pop(0)
                    st["old_sks"].append(s)
                    st["old_yks"].append(y)
                    st["ro"].append(1.0 / ys)
                    st["H_diag"] = ys / float(jnp.dot(y, y))
                q = -flat_grad
                alphas = []
                for s_i, y_i, ro_i in zip(reversed(st["old_sks"]),
                                          reversed(st["old_yks"]),
                                          reversed(st["ro"])):
                    alpha = ro_i * float(jnp.dot(s_i, q))
                    alphas.append(alpha)
                    q = q - alpha * y_i
                d = q * st["H_diag"]
                for (s_i, y_i, ro_i), alpha in zip(
                        zip(st["old_sks"], st["old_yks"], st["ro"]),
                        reversed(alphas)):
                    beta = ro_i * float(jnp.dot(y_i, d))
                    d = d + s_i * (alpha - beta)
            st["prev_flat_grad"] = flat_grad
            prev_loss = loss

            # --- step size
            if st["n_iter"] == 1:
                t = min(1.0, 1.0 / float(jnp.sum(jnp.abs(flat_grad)))) * lr
            else:
                t = lr
            gtd = float(jnp.dot(flat_grad, d))
            if gtd > -self._tol_change:
                break

            if self._line_search_fn == "strong_wolfe":
                snapshot = self._clone_params()

                def obj(alpha):
                    self._restore_params(snapshot)
                    self._add_delta(alpha, d)
                    l = float(closure_fn())
                    g = self._gather_flat_grad()
                    return l, g

                loss, flat_grad, t, ls_evals = _strong_wolfe(
                    obj, t, d, loss, flat_grad, gtd)
                self._restore_params(snapshot)
                self._add_delta(t, d)
                current_evals += ls_evals
                st["func_evals"] += ls_evals
            else:
                self._add_delta(t, d)
                if n_iter != self._max_iter:
                    loss = float(closure_fn())
                    flat_grad = self._gather_flat_grad()
                    current_evals += 1
                    st["func_evals"] += 1
            st["d"], st["t"] = d, t

            if current_evals >= self._max_eval:
                break
            if float(jnp.max(jnp.abs(flat_grad))) <= self._tol_grad:
                break
            if float(jnp.max(jnp.abs(d * t))) <= self._tol_change:
                break
            if abs(loss - prev_loss) < self._tol_change:
                break
        self._step_count += 1
        return loss


def _hermite_min(a, fa, sa, b, fb, sb, lo, hi):
    """Minimizer of the cubic Hermite interpolant through (a, fa, sa) and
    (b, fb, sb), clamped to [lo, hi].

    Derivation: parametrize tau in [0, 1] over the (a, b) span h = b - a,
    p(tau) = c0 + c1*tau + c2*tau^2 + c3*tau^3 with
      c0 = fa, c1 = h*sa,
      c2 = 3*(fb - fa) - h*(2*sa + sb),
      c3 = h*(sa + sb) - 2*(fb - fa),
    and take the p'(tau) = 0 root with p'' > 0; bisect when the
    interpolant has no interior minimum."""
    h = b - a
    if h == 0.0:
        return max(lo, min(hi, a))
    df = fb - fa
    c1 = h * sa
    c2 = 3.0 * df - h * (2.0 * sa + sb)
    c3 = h * (sa + sb) - 2.0 * df
    cand = None
    if abs(c3) > 1e-20:
        disc = c2 * c2 - 3.0 * c3 * c1
        if disc >= 0.0:
            # root with positive curvature: p'' = 2 c2 + 6 c3 tau > 0
            r = disc ** 0.5
            tau = (-c2 + r) / (3.0 * c3)
            if 2.0 * c2 + 6.0 * c3 * tau < 0.0:
                tau = (-c2 - r) / (3.0 * c3)
            cand = a + tau * h
    elif abs(c2) > 1e-20 and c2 > 0.0:
        cand = a + (-c1 / (2.0 * c2)) * h
    if cand is None or not (lo <= cand <= hi):
        cand = 0.5 * (lo + hi)
    return cand


def _strong_wolfe(obj_func, t, d, f, g, gtd, c1=1e-4, c2=0.9,
                  tolerance_change=1e-9, max_ls=25):
    """Strong-Wolfe line search along direction d.

    Two phases (Nocedal & Wright, Alg. 3.5/3.6 shape): an expansion walk
    that either accepts the trial, brackets a minimum, or grows the step;
    then a zoom on the bracket using the Hermite-cubic candidate with a
    central-interval safeguard.  obj_func(step) -> (value, flat_grad).
    Returns (value, flat_grad, step, n_evals)."""
    scale = float(jnp.max(jnp.abs(d)))

    def probe(step):
        val, grad = obj_func(step)
        return val, grad, float(jnp.dot(grad, d))

    def armijo_ok(step, val):
        return val <= f + c1 * step * gtd

    def curvature_ok(slope):
        return abs(slope) <= -c2 * gtd

    evals = 0
    prev = (0.0, f, jnp.asarray(g), gtd)   # (step, value, grad, slope)
    cur_v, cur_g, cur_s = probe(t)
    evals += 1
    cur = (t, cur_v, cur_g, cur_s)

    span = None
    for k in range(max_ls):
        st, v, gr, sl = cur
        if not armijo_ok(st, v) or (k > 0 and v >= prev[1]):
            span = (prev, cur)        # overshot: minimum is inside
            break
        if curvature_ok(sl):
            return v, gr, st, evals   # Wolfe pair satisfied outright
        if sl >= 0.0:
            span = (cur, prev)        # slope flipped: bracketed
            break
        # still descending: extrapolate beyond the current step
        grow = _hermite_min(prev[0], prev[1], prev[3], st, v, sl,
                            st + 0.1 * (st - prev[0]), 4.0 * st)
        prev = cur
        nv, ng, ns = probe(grow)
        evals += 1
        cur = (grow, nv, ng, ns)
    if span is None:
        # expansion exhausted: fall back to the best endpoint seen
        span = ((0.0, f, jnp.asarray(g), gtd), cur)

    lo, hi = span if span[0][1] <= span[1][1] else (span[1], span[0])
    while evals < max_ls and not curvature_ok(lo[3]):
        width = abs(hi[0] - lo[0])
        if width * scale < tolerance_change:
            break
        a, b = (lo, hi) if lo[0] < hi[0] else (hi, lo)
        cand = _hermite_min(a[0], a[1], a[3], b[0], b[1], b[3],
                            a[0], b[0])
        # keep the trial inside the central 80% of the bracket so the
        # interval provably shrinks (bisect otherwise)
        margin = 0.1 * width
        if not (a[0] + margin <= cand <= b[0] - margin):
            cand = 0.5 * (a[0] + b[0])
        nv, ng, ns = probe(cand)
        evals += 1
        trial = (cand, nv, ng, ns)
        if not armijo_ok(cand, nv) or nv >= lo[1]:
            hi = trial                # sufficient-decrease side shrinks
        else:
            if curvature_ok(ns):
                lo = trial
                break
            if ns * (hi[0] - lo[0]) >= 0.0:
                hi = lo               # minimum is on the other side
            lo = trial
    return lo[1], lo[2], lo[0], evals
