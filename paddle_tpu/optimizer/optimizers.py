"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,adam,
adamw,adagrad,adadelta,rmsprop,lamb}.py).  Math matches the reference kernels
(paddle/phi/kernels/*_kernel.cc) including AdamW's decoupled decay and Lamb's
trust ratio."""
from __future__ import annotations

import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adagrad", "Adadelta",
           "RMSProp", "Lamb"]


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        self._write_back(p, x - lr * g)

    def _update_param_rowsparse(self, p, g, lr):
        # reference sgd SelectedRows kernel (sgd_kernel.cc DenseParam+
        # SparseGrad branch): update touched rows only; L2 decay applies
        # to touched rows (regularizer-on-rows semantics)
        x = self._param_f32(p)
        m = g.merged()
        vals = m.values.astype(jnp.float32)
        if self._coeff:
            vals = vals + self._coeff * jnp.take(x, m.rows, axis=0,
                                                 mode="clip")
        self._write_back(p, x.at[m.rows].add(-lr * vals, mode="drop"))


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        v = self._acc(p, "velocity")
        v = self._momentum * v + g
        self._set_acc(p, "velocity", v)
        if self._nesterov:
            update = g + self._momentum * v
        else:
            update = v
        self._write_back(p, x - lr * update)


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lazy_mode = bool(lazy_mode)

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:  # L2 regularization folded into grad (Adam semantics)
            g = g + self._coeff * x
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc(p, "moment1", m)
        self._set_acc(p, "moment2", v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        self._write_back(p, x - lr * mhat / (jnp.sqrt(vhat) + self._epsilon))

    def _update_param_rowsparse(self, p, g, lr):
        # reference adam lazy_mode (adam_kernel SelectedRows branch):
        # moments decay and the param moves ONLY on touched rows; untouched
        # rows are exactly unchanged.  Without lazy_mode, densify (the
        # reference's non-lazy sparse adam also updates every row).
        if not self._lazy_mode:
            return super()._update_param_rowsparse(p, g, lr)
        x = self._param_f32(p)
        mg = g.merged()
        rows = mg.rows
        vals = mg.values.astype(jnp.float32)
        if self._coeff:
            vals = vals + self._coeff * jnp.take(x, rows, axis=0,
                                                 mode="clip")
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        mr = self._beta1 * jnp.take(m, rows, axis=0, mode="clip") \
            + (1 - self._beta1) * vals
        vr = self._beta2 * jnp.take(v, rows, axis=0, mode="clip") \
            + (1 - self._beta2) * jnp.square(vals)
        self._set_acc(p, "moment1", m.at[rows].set(mr, mode="drop"))
        self._set_acc(p, "moment2", v.at[rows].set(vr, mode="drop"))
        mhat = mr / (1 - self._beta1 ** t)
        vhat = vr / (1 - self._beta2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        self._write_back(p, x.at[rows].add(-upd, mode="drop"))


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name)
        self._wd = float(weight_decay) if isinstance(weight_decay, (int, float)) \
            else float(getattr(weight_decay, "_coeff", 0.01))
        self._apply_decay_fun = apply_decay_param_fun
        self._lr_ratio = lr_ratio

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        # decoupled weight decay (reference adamw kernel: param *= 1 - lr*wd)
        if self._wd and (self._apply_decay_fun is None or
                         self._apply_decay_fun(p.name)):
            x = x * (1.0 - lr * self._wd)
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc(p, "moment1", m)
        self._set_acc(p, "moment2", v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        self._write_back(p, x - lr * mhat / (jnp.sqrt(vhat) + self._epsilon))

    def _update_param_rowsparse(self, p, g, lr):
        # lazy AdamW: decoupled decay also restricted to touched rows so
        # untouched rows stay bit-identical (lazy contract)
        if not self._lazy_mode:
            return Optimizer._update_param_rowsparse(self, p, g, lr)
        x = self._param_f32(p)
        if self._lr_ratio is not None:
            lr = lr * self._lr_ratio(p)
        mg = g.merged()
        rows = mg.rows
        vals = mg.values.astype(jnp.float32)
        xr = jnp.take(x, rows, axis=0, mode="clip")
        if self._wd and (self._apply_decay_fun is None or
                         self._apply_decay_fun(p.name)):
            # param rows decay before the adam move (reference kernel order)
            x = x.at[rows].add(-lr * self._wd * xr, mode="drop")
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        mr = self._beta1 * jnp.take(m, rows, axis=0, mode="clip") \
            + (1 - self._beta1) * vals
        vr = self._beta2 * jnp.take(v, rows, axis=0, mode="clip") \
            + (1 - self._beta2) * jnp.square(vals)
        self._set_acc(p, "moment1", m.at[rows].set(mr, mode="drop"))
        self._set_acc(p, "moment2", v.at[rows].set(vr, mode="drop"))
        mhat = mr / (1 - self._beta1 ** t)
        vhat = vr / (1 - self._beta2 ** t)
        upd = lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        self._write_back(p, x.at[rows].add(-upd, mode="drop"))


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        acc = self._acc(p, "moment",
                        jnp.full(p._data.shape, self._init_acc, jnp.float32))
        acc = acc + jnp.square(g)
        self._set_acc(p, "moment", acc)
        self._write_back(p, x - lr * g / (jnp.sqrt(acc) + self._epsilon))


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._epsilon = epsilon
        self._rho = rho

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        avg_sq = self._acc(p, "avg_squared_grad")
        avg_upd = self._acc(p, "avg_squared_update")
        avg_sq = self._rho * avg_sq + (1 - self._rho) * jnp.square(g)
        update = -jnp.sqrt((avg_upd + self._epsilon) /
                           (avg_sq + self._epsilon)) * g
        avg_upd = self._rho * avg_upd + (1 - self._rho) * jnp.square(update)
        self._set_acc(p, "avg_squared_grad", avg_sq)
        self._set_acc(p, "avg_squared_update", avg_upd)
        self._write_back(p, x + lr * update)


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        if self._coeff:
            g = g + self._coeff * x
        ms = self._acc(p, "mean_square")
        mom = self._acc(p, "momentum")
        ms = self._rho * ms + (1 - self._rho) * jnp.square(g)
        self._set_acc(p, "mean_square", ms)
        if self._centered:
            mg = self._acc(p, "mean_grad")
            mg = self._rho * mg + (1 - self._rho) * g
            self._set_acc(p, "mean_grad", mg)
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * mom + lr * g / denom
        self._set_acc(p, "momentum", mom)
        self._write_back(p, x - mom)


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 multi_precision=True, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision, name)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _update_param(self, p, g, lr):
        x = self._param_f32(p)
        m = self._acc(p, "moment1")
        v = self._acc(p, "moment2")
        t = self._step_count + 1
        m = self._beta1 * m + (1 - self._beta1) * g
        v = self._beta2 * v + (1 - self._beta2) * jnp.square(g)
        self._set_acc(p, "moment1", m)
        self._set_acc(p, "moment2", v)
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        r = mhat / (jnp.sqrt(vhat) + self._epsilon)
        wd = 0.0 if (self._exclude_fn is not None and self._exclude_fn(p)) \
            else self._wd
        update = r + wd * x
        w_norm = jnp.linalg.norm(x)
        u_norm = jnp.linalg.norm(update)
        trust = jnp.where(jnp.logical_and(w_norm > 0, u_norm > 0),
                          w_norm / u_norm, 1.0)
        self._write_back(p, x - lr * trust * update)
