"""Tail-latency forensics: per-request lifecycle timelines, critical-
path attribution, and SLO-violation exemplars.

The aggregate surfaces (burn rates, p99 tables, phase-attributed
profiles) say *that* the tail is bad; this module answers the
operator's first question — **why was request X slow?**

  * :class:`RequestTimeline` — a bounded per-request event list stamped
    on the ENGINE clock at the seams the engine already instruments
    (submit / admit / prefill / chunk / preempt / resume / host-sync /
    recovery replay / finalize), folded into a **critical-path
    attribution**: exact second buckets :data:`BUCKETS` whose sum
    equals the measured E2E (``finished_at - arrival_time``) by
    construction — every ``note`` charges the interval since a single
    advancing cursor, so the bucket sums telescope to the request's
    wall clock.  Conservation is checked like the usage meter's
    page-second law: ``round(sum(buckets) - e2e, 6) == 0``.
  * :class:`ExemplarStore` — a bounded worst-K reservoir per SLO
    dimension (ttft/tpot/e2e) and per ``finish_reason="error"``, keyed
    by tenant/adapter/priority, snapshotting the full timeline +
    attribution whenever the SLOTracker records a violation (wired to
    ``SLOTracker.exemplar_hook``).  Each record carries the violating
    request's trace id, so ``/debug/trace`` and ``/debug/exemplars``
    cross-reference by one id.
  * :class:`RequestLog` — the engine-attached container: a bounded
    id -> timeline map behind ``GET /debug/requests/<id>`` (waterfall
    JSON + chrome-trace export), the exemplar store behind
    ``GET /debug/exemplars``, and the
    ``serving_latency_attribution_seconds_total{cause}`` counter.

Zero-overhead-off: the engine holds ``requestlog=None`` by default and
every hot-path site is a single ``is not None`` test (the faults /
usage / slo guard pattern); armed-mode cost is pinned by the
``tail_forensics`` perf-gate scenario.
"""
from __future__ import annotations

from collections import OrderedDict

from ..sanitizer import make_lock
from .registry import default_registry

__all__ = ["BUCKETS", "ExemplarStore", "RequestLog", "RequestTimeline",
           "merge_exemplars", "active_requestlog",
           "set_active_requestlog"]

# the nine critical-path causes a request's E2E decomposes into; their
# per-request sum equals finished_at - arrival_time exactly (network is
# the router-side bucket — 0.0 for in-process requests)
BUCKETS = ("queue", "prefill_compute", "prefill_cached", "chunk_gap",
           "preempted", "host_sync", "decode", "recovery", "network")

_M_ATTR = default_registry().counter(
    "serving_latency_attribution_seconds_total",
    "request wall seconds by critical-path cause: per-request E2E "
    "decomposed into queue wait, prefill compute vs prefix-cache "
    "credit, chunked-prefill gaps, preemption (spill + re-queue + "
    "restore), blocking host syncs, decode, recovery replays, and "
    "router hops — buckets sum to serving_e2e_seconds' mass",
    ("cause",))


class RequestTimeline:
    """One request's lifecycle on the engine clock.

    ``note(bucket, t)`` charges the interval since the cursor (which
    starts at ``arrival_time``) to ``bucket`` and advances the cursor
    to ``t`` — attribution conservation holds by construction because
    the cursor only moves forward and every second between arrival and
    finish is charged exactly once.  The event list is bounded
    (``max_events``); overflow drops *events* (counted), never bucket
    seconds.
    """

    __slots__ = ("req_id", "trace_id", "tenant", "adapter", "priority",
                 "arrival_time", "buckets", "events", "events_dropped",
                 "max_events", "_cursor", "_residual", "finished",
                 "finish_reason", "e2e_s")

    def __init__(self, req, *, max_events: int = 256):
        self.req_id = req.id
        self.trace_id = (req.root_span.trace_id
                         if req.root_span is not None else None)
        self.tenant = getattr(req, "tenant", "anon")
        self.adapter = getattr(req, "adapter", None)
        self.priority = getattr(req, "priority", 0)
        self.arrival_time = req.arrival_time
        self.buckets = {b: 0.0 for b in BUCKETS}
        self.events: list[dict] = []
        self.events_dropped = 0
        self.max_events = int(max_events)
        self._cursor = req.arrival_time
        self._residual = "queue"        # bucket an eviction charges now
        self.finished = False
        self.finish_reason = None
        self.e2e_s = None
        self._event("submit", req.arrival_time, 0.0, None,
                    prompt_len=int(req.prompt.size))

    # ------------------------------------------------------------ recording
    def _event(self, kind: str, t: float, dur: float,
               bucket: str | None, **attrs):
        if len(self.events) >= self.max_events:
            self.events_dropped += 1
            return
        ev = {"event": kind, "t": round(t - self.arrival_time, 6),
              "dur": round(dur, 6)}
        if bucket is not None:
            ev["bucket"] = bucket
        if attrs:
            ev.update(attrs)
        self.events.append(ev)

    def note(self, bucket: str, t: float, *, event: str | None = None,
             then: str | None = None, **attrs) -> float:
        """Charge ``[cursor, t]`` to ``bucket``; optionally record an
        event.  ``then`` names the bucket a finalize would charge the
        *next* interval to (the request's state after this seam)."""
        dt = max(t - self._cursor, 0.0)
        self.buckets[bucket] += dt
        self._cursor = max(self._cursor, t)
        if then is not None:
            self._residual = then
        if event is not None:
            self._event(event, self._cursor, dt, bucket, **attrs)
        return dt

    def note_prefill(self, t: float, *, cached: int, computed: int,
                     event: str = "prefill", **attrs):
        """Charge the prefill interval split between compute and the
        prefix-cache credit by token share — cached tokens cost no
        device work, so their share of the wall is the cache's win."""
        total = max(cached + computed, 1)
        frac = cached / total
        dt = max(t - self._cursor, 0.0)
        self.buckets["prefill_cached"] += dt * frac
        self.buckets["prefill_compute"] += dt * (1.0 - frac)
        self._cursor = max(self._cursor, t)
        self._residual = "decode"
        self._event(event, self._cursor, dt, "prefill_compute",
                    cached_tokens=int(cached),
                    computed_tokens=int(computed), **attrs)

    def note_sync(self, t: float, sync_s: float):
        """One host sync observed while decoding: split the interval
        since the cursor at ``t - sync_s`` — the earlier part was
        decode dispatch, the blocking ring fetch was the sync."""
        dt = max(t - self._cursor, 0.0)
        sync_part = min(max(sync_s, 0.0), dt)
        self.buckets["decode"] += dt - sync_part
        self.buckets["host_sync"] += sync_part
        self._cursor = max(self._cursor, t)
        self._residual = "decode"
        self._event("host_sync", self._cursor, dt, "host_sync",
                    sync_s=round(sync_part, 6))

    def mark(self, kind: str, t: float, **attrs):
        """Zero-duration marker (first token, eviction reason, ...) —
        no bucket charge, the cursor does not move."""
        self._event(kind, t, 0.0, None, **attrs)

    def finish(self, reason: str, now: float):
        """Charge the residual interval to the bucket of the state the
        request died in, stamp the outcome, and freeze the timeline."""
        self.note(self._residual, now, event="finish", reason=reason)
        self.finished = True
        self.finish_reason = reason
        self.e2e_s = now - self.arrival_time

    # ------------------------------------------------------------ reporting
    def attribution(self) -> dict:
        return dict(self.buckets)

    def conservation_delta(self) -> float:
        """``sum(buckets) - measured E2E`` — 0.0 (to 6 decimals) for
        every finished request, the page-second-law analog."""
        if self.e2e_s is None:
            return 0.0
        return round(sum(self.buckets.values()) - self.e2e_s, 6)

    def to_dict(self) -> dict:
        """The waterfall JSON behind ``GET /debug/requests/<id>``."""
        return {
            "request": self.req_id,
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "adapter": self.adapter,
            "priority": self.priority,
            "arrival_time": self.arrival_time,
            "finished": self.finished,
            "finish_reason": self.finish_reason,
            "e2e_s": (None if self.e2e_s is None
                      else round(self.e2e_s, 6)),
            "attribution": {b: round(v, 6)
                            for b, v in self.buckets.items()},
            "conservation_delta": self.conservation_delta(),
            "events": list(self.events),
            "events_dropped": self.events_dropped,
        }

    def chrome_trace(self) -> dict:
        """chrome://tracing-loadable export: one complete ("X") event
        per charged timeline event, offset from arrival in µs."""
        trace = []
        for ev in self.events:
            dur_us = ev["dur"] * 1e6
            trace.append({
                "name": ev["event"], "ph": "X", "cat": "request",
                "ts": (ev["t"] * 1e6) - dur_us, "dur": dur_us,
                "pid": 1, "tid": self.req_id,
                "args": {k: v for k, v in ev.items()
                         if k not in ("event", "t", "dur")}})
        return {"traceEvents": trace, "request": self.req_id,
                "trace_id": self.trace_id}


class ExemplarStore:
    """Bounded worst-K reservoir of violating requests per SLO
    dimension (ttft/tpot/e2e) plus ``finish_reason="error"`` — each
    record snapshots the full timeline + attribution at capture time
    and carries the request's trace id for the ``/debug/trace`` join."""

    DIMENSIONS = ("ttft", "tpot", "e2e", "error")

    def __init__(self, k: int = 8):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.k = int(k)
        self._lock = make_lock("ExemplarStore._lock")
        self._worst: dict[str, list[dict]] = {
            d: [] for d in self.DIMENSIONS}
        self.offered = 0
        self.kept = 0

    def offer(self, dim: str, score_s: float, timeline: RequestTimeline):
        """Consider one violating request for the ``dim`` reservoir;
        kept while it ranks among the worst K by ``score_s``."""
        record = {
            "dimension": dim,
            "score_s": round(float(score_s), 6),
            "request": timeline.req_id,
            "trace_id": timeline.trace_id,
            "tenant": timeline.tenant,
            "adapter": timeline.adapter,
            "priority": timeline.priority,
            "captured_at": timeline.arrival_time
            + (timeline.e2e_s or 0.0),
            "timeline": timeline.to_dict(),
        }
        with self._lock:
            self.offered += 1
            worst = self._worst[dim]
            worst.append(record)
            worst.sort(key=lambda r: (-r["score_s"], r["request"]))
            if len(worst) > self.k:
                worst.pop()
            if record in worst:
                self.kept += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "k": self.k,
                "offered": self.offered,
                "kept": self.kept,
                "by_dimension": {d: [dict(r) for r in lst]
                                 for d, lst in self._worst.items()},
            }


def merge_exemplars(snapshots, *, k: int | None = None) -> dict:
    """Raw-merge per-replica exemplar snapshots for the router view:
    per-dimension lists concatenate and re-rank worst-first (never
    averaging), counters sum.  ``None`` entries (dead replicas,
    forensics off) are skipped — the /debug/fleet stale-nulling
    discipline."""
    by_dim: dict[str, list[dict]] = {d: [] for d in
                                     ExemplarStore.DIMENSIONS}
    offered = kept = 0
    cap = 0
    live = 0
    for snap in snapshots:
        if not isinstance(snap, dict) or "by_dimension" not in snap:
            continue
        live += 1
        cap = max(cap, int(snap.get("k") or 0))
        offered += int(snap.get("offered") or 0)
        kept += int(snap.get("kept") or 0)
        for d, lst in snap["by_dimension"].items():
            by_dim.setdefault(d, []).extend(lst)
    cap = k if k is not None else max(cap, 1)
    for d, lst in by_dim.items():
        lst.sort(key=lambda r: (-r.get("score_s", 0.0),
                                r.get("request", 0)))
        del lst[cap:]
    return {"k": cap, "offered": offered, "kept": kept,
            "replicas_merged": live, "by_dimension": by_dim}


class RequestLog:
    """The engine-attached forensics container (``requestlog=`` /
    ``FLAGS_serving_request_log``): a bounded id -> timeline map plus
    the exemplar reservoir.  One instance per engine; the last engine
    built wins the process-active slot (``obs.set_active_requestlog``)
    so ``obs.dump()`` writes ``exemplars.json`` from it."""

    def __init__(self, *, max_requests: int = 512,
                 max_events: int = 256, k: int = 8):
        if max_requests < 1:
            raise ValueError(
                f"max_requests must be >= 1, got {max_requests}")
        self.max_requests = int(max_requests)
        self.max_events = int(max_events)
        self._lock = make_lock("RequestLog._lock")
        self._timelines: OrderedDict[int, RequestTimeline] = \
            OrderedDict()
        self.exemplars = ExemplarStore(k=k)
        self.events_total = 0           # perf-gate witness
        self.finished = 0
        self.evicted_timelines = 0
        self.recovery_sweeps = 0        # supervisor recover() passes
        # worst conservation miss ever observed (must stay 0.0)
        self.conservation_max_delta = 0.0
        # running per-cause totals across finished requests (python
        # mirror of serving_latency_attribution_seconds_total)
        self.bucket_totals = {b: 0.0 for b in BUCKETS}

    # ------------------------------------------------------- engine seams
    def attach(self, req) -> RequestTimeline:
        """Create and register ``req``'s timeline (engine.submit)."""
        tl = RequestTimeline(req, max_events=self.max_events)
        with self._lock:
            self._timelines[req.id] = tl
            while len(self._timelines) > self.max_requests:
                self._timelines.popitem(last=False)
                self.evicted_timelines += 1
        req.timeline = tl
        return tl

    def discard(self, req_id: int):
        """Drop a timeline registered by a submit that then failed."""
        with self._lock:
            self._timelines.pop(req_id, None)

    def on_finish(self, req, reason: str, now: float):
        """Engine._finalize seam: close the timeline, fold its buckets
        into the attribution counter, track conservation, and capture
        an error exemplar when the request was quarantined."""
        tl = req.timeline
        if tl is None or tl.finished:
            return
        tl.finish(reason, now)
        with self._lock:
            self.finished += 1
            self.events_total += len(tl.events) + tl.events_dropped
            delta = abs(tl.conservation_delta())
            if delta > self.conservation_max_delta:
                self.conservation_max_delta = delta
            for bucket, seconds in tl.buckets.items():
                self.bucket_totals[bucket] += seconds
        for bucket, seconds in tl.buckets.items():
            if seconds > 0.0:
                _M_ATTR.labels(bucket).inc(seconds)
        if reason == "error":
            self.exemplars.offer("error", tl.e2e_s or 0.0, tl)

    def slo_verdict(self, req, dim: str, ok: bool,
                    value: float | None = None):
        """``SLOTracker.exemplar_hook`` adapter: snapshot the violating
        request's timeline into the ``dim`` reservoir.  ``value`` is
        the measured latency the tracker already computed (None when a
        request never produced a first token)."""
        if ok:
            return
        tl = getattr(req, "timeline", None)
        if tl is None:
            return
        self.exemplars.offer(dim, value if value is not None
                             else (tl.e2e_s or 0.0), tl)

    def note_recovery(self, result: dict | None = None):
        """Supervisor seam: count one recovery sweep (the per-request
        replay seconds land in each timeline's ``recovery`` bucket)."""
        with self._lock:
            self.recovery_sweeps += 1

    # ---------------------------------------------------------- reporting
    def get(self, req_id: int) -> RequestTimeline | None:
        with self._lock:
            return self._timelines.get(req_id)

    def timelines(self) -> list[RequestTimeline]:
        with self._lock:
            return list(self._timelines.values())

    def snapshot(self) -> dict:
        """``GET /debug/exemplars`` / ``exemplars.json`` payload."""
        with self._lock:
            tracked = len(self._timelines)
            finished = self.finished
            events_total = self.events_total
            evicted = self.evicted_timelines
            sweeps = self.recovery_sweeps
            delta = round(self.conservation_max_delta, 6)
            totals = {b: round(v, 6)
                      for b, v in self.bucket_totals.items()}
        return {
            "requests_tracked": tracked,
            "finished": finished,
            "events_total": events_total,
            "evicted_timelines": evicted,
            "recovery_sweeps": sweeps,
            "conservation_max_delta": delta,
            "attribution_totals_s": totals,
            "exemplars": self.exemplars.snapshot(),
        }

    def tail_summary(self, now: float | None = None) -> dict | None:
        """The fleet-summary ``tail`` block: the dominant cause across
        finished requests plus the single worst exemplar (``age_s`` on
        the engine clock when ``now`` is given).  None until a request
        finishes — the dashboard prints nothing for idle replicas."""
        with self._lock:
            if not self.finished:
                return None
            totals = dict(self.bucket_totals)
            delta = round(self.conservation_max_delta, 6)
            finished = self.finished
        top = max(totals, key=lambda b: totals[b])
        worst = None
        for lst in self.exemplars.snapshot()["by_dimension"].values():
            for rec in lst:
                if worst is None or rec["score_s"] > worst["score_s"]:
                    worst = rec
        if worst is not None:
            worst = {k: worst[k] for k in
                     ("dimension", "score_s", "request", "trace_id",
                      "tenant", "adapter", "captured_at")}
            if now is not None:
                worst["age_s"] = round(
                    max(now - worst["captured_at"], 0.0), 6)
        return {"finished": finished,
                "top_cause": top,
                "top_cause_s": round(totals[top], 6),
                "attribution_totals_s": {b: round(v, 6)
                                         for b, v in totals.items()},
                "conservation_max_delta": delta,
                "worst_exemplar": worst}


# the process-active request log: obs.dump() writes exemplars.json
# from it (last engine built wins — the profiler/usage holder contract)
_active_requestlog: RequestLog | None = None


def set_active_requestlog(log: RequestLog | None):
    global _active_requestlog
    _active_requestlog = log


def active_requestlog() -> RequestLog | None:
    return _active_requestlog
