"""Fleet observability: bounded-ring time series + anomaly alert rules.

Every signal the runtime exports today is an *instantaneous* snapshot
— the registry value right now, the burn rate right now.  This module
adds history and judgement on top of the same registry:

  * :class:`Series` — a bounded ring of ``(t, value)`` points with
    window / ``delta()`` / ``rate()`` queries (monotonic clock, fixed
    memory; the time-series analog of the flight recorder's ring).
  * :class:`TimeSeriesStore` — samples registered *sources* (callables,
    typically registry read-backs via :func:`metric_value`) on an
    explicit ``tick(now)``.  Production drives ticks from a sampler
    thread (``start_sampling``); tests drive them from a fake clock —
    the same split the serving watchdog uses, so nothing here ever
    sleeps in a unit test.
  * :class:`AlertRule` — threshold (``kind="value"``) and derivative
    (``kind="rate"``) rules over any series, with an optional ``when``
    gate (e.g. "tok/s collapsed *while slots were active*").  Each
    fire/clear transition bumps ``obs_alerts_total{rule}``, flips
    ``obs_alert_firing{rule}``, and stamps an ``alert`` event into the
    flight recorder; a clear -> firing edge additionally invokes the
    store's optional ``on_fire`` hook (capture.DiagnosticCapture
    snapshots its evidence bundle there); firing rules surface on
    ``/healthz`` and in the ``/debug/fleet`` replica summary.

Sampling reads values *back from the metrics registry* (the same
watchdog-safe pattern as resources._pool_from_registry) — never from
engine internals — so a tick takes no engine lock, triggers no device
work, and adds zero host syncs (gated by the perf_gate ``telemetry``
scenario).  With ``FLAGS_obs_timeseries_interval_s`` unset no store is
ever constructed: the serving path's only cost is an attribute test,
the same zero-overhead contract as fault injection and the sanitizer.

In-process multi-replica tests share one registry, so registry-backed
sources (and therefore alerts) reflect the *process*, not one replica;
production replicas are separate processes where the two coincide.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from ..sanitizer import make_lock
from .registry import default_registry
from .tracing import flight_recorder

__all__ = ["AlertRule", "Series", "TimeSeriesStore", "default_rules",
           "metric_value", "serving_sources"]

_M_SAMPLES = default_registry().counter(
    "obs_timeseries_samples_total",
    "points appended to time-series rings by sampler ticks")
_M_ALERTS = default_registry().counter(
    "obs_alerts_total",
    "alert-rule fire transitions (clear -> firing), by rule", ("rule",))
_M_FIRING = default_registry().gauge(
    "obs_alert_firing",
    "1 while the named alert rule is firing, 0 otherwise", ("rule",))


def metric_value(name, labels=None, registry=None):
    """Read one registry family back as a scalar: the sum of its series
    values, optionally filtered to series whose labels contain the
    ``labels`` subset.  None when the family is not registered (the
    store skips the sample) or is a histogram."""
    reg = registry or default_registry()
    m = reg.get(name)
    if m is None or m.kind == "histogram":
        return None
    want = tuple(sorted((labels or {}).items()))
    total = 0.0
    for labelvalues, child in m._series():
        if want:
            have = dict(zip(m.labelnames, labelvalues))
            if any(have.get(k) != str(v) for k, v in want):
                continue
        total += child.value
    return total


class Series:
    """Bounded ring of ``(t, value)`` samples, newest last."""

    __slots__ = ("name", "_points")

    def __init__(self, name: str, capacity: int = 512):
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.name = name
        # deque appends are atomic and points() snapshots via list(),
        # so readers never see a torn ring (same contract as the
        # flight recorder)
        self._points: deque = deque(maxlen=int(capacity))

    def add(self, t: float, value: float):
        self._points.append((float(t), float(value)))

    def __len__(self):
        return len(self._points)

    def last(self):
        """Newest ``(t, value)`` or None when empty."""
        try:
            return self._points[-1]
        except IndexError:
            return None

    def points(self, window_s: float | None = None,
               now: float | None = None) -> list:
        """Samples newest-last; ``window_s`` keeps only points within
        the trailing window ending at ``now`` (default: newest t)."""
        pts = list(self._points)
        if window_s is None or not pts:
            return pts
        end = pts[-1][0] if now is None else float(now)
        return [p for p in pts if p[0] >= end - float(window_s)]

    def delta(self, window_s: float | None = None,
              now: float | None = None):
        """last - first value over the window; None with < 2 points."""
        pts = self.points(window_s, now)
        if len(pts) < 2:
            return None
        return pts[-1][1] - pts[0][1]

    def rate(self, window_s: float | None = None,
             now: float | None = None):
        """(last - first) / elapsed over the window, per second; None
        with < 2 points or zero elapsed time."""
        pts = self.points(window_s, now)
        if len(pts) < 2:
            return None
        dt = pts[-1][0] - pts[0][0]
        if dt <= 0:
            return None
        return (pts[-1][1] - pts[0][1]) / dt

    def rate_points(self, window_s: float | None = None,
                    now: float | None = None) -> list:
        """Per-interval rates between consecutive samples — the
        sparkline view of a counter series."""
        pts = self.points(window_s, now)
        out = []
        for (t0, v0), (t1, v1) in zip(pts, pts[1:]):
            if t1 > t0:
                out.append((t1, (v1 - v0) / (t1 - t0)))
        return out


_OPS = {">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
        "<": lambda a, b: a < b, "<=": lambda a, b: a <= b}


class AlertRule:
    """One anomaly rule over one series.

    ``kind="value"`` compares the newest sample against the threshold;
    ``kind="rate"`` compares the per-second rate over the trailing
    ``window_s``.  Exactly one of ``above`` / ``below`` sets the
    threshold.  ``when`` optionally gates evaluation on another
    series' newest sample, e.g. ``("active_slots", ">", 0)`` so a
    tok/s collapse only fires while work was actually resident.
    ``min_samples`` suppresses firing until the series has history.
    """

    def __init__(self, name: str, series: str, *, above=None,
                 below=None, kind: str = "value",
                 window_s: float = 30.0, min_samples: int = 2,
                 when: tuple | None = None, help_: str = ""):
        if (above is None) == (below is None):
            raise ValueError(
                f"rule {name!r}: pass exactly one of above= / below=")
        if kind not in ("value", "rate"):
            raise ValueError(
                f"rule {name!r}: kind must be 'value' or 'rate', "
                f"got {kind!r}")
        if when is not None and (len(when) != 3 or when[1] not in _OPS):
            raise ValueError(
                f"rule {name!r}: when= must be (series, op, value) "
                f"with op in {sorted(_OPS)}")
        self.name = name
        self.series = series
        self.kind = kind
        self.op = "<" if above is None else ">"
        self.threshold = float(below if above is None else above)
        self.window_s = float(window_s)
        self.min_samples = max(int(min_samples), 2 if kind == "rate"
                               else 1)
        self.when = when
        self.help = help_

    def measure(self, store: "TimeSeriesStore", now: float):
        """Current comparison value, or None when the rule cannot be
        evaluated yet (missing series, too few samples, gate closed)."""
        s = store.series.get(self.series)
        if s is None or len(s) < self.min_samples:
            return None
        if self.when is not None:
            gate = store.series.get(self.when[0])
            last = gate.last() if gate is not None else None
            if last is None or not _OPS[self.when[1]](
                    last[1], float(self.when[2])):
                return None
        if self.kind == "rate":
            return s.rate(self.window_s, now)
        last = s.last()
        return None if last is None else last[1]

    def check(self, store: "TimeSeriesStore", now: float) -> bool:
        v = self.measure(store, now)
        return v is not None and _OPS[self.op](v, self.threshold)

    def describe(self) -> dict:
        return {"name": self.name, "series": self.series,
                "kind": self.kind,
                "condition": f"{self.kind}({self.series})"
                             f" {self.op} {self.threshold:g}",
                "window_s": self.window_s, "help": self.help}


class TimeSeriesStore:
    """Sources + rings + alert rules, advanced by explicit ticks.

    ``clock`` defaults to ``time.monotonic``; tests pass a fake.  The
    lock covers registration and tick bookkeeping — sources run
    *outside* any engine lock by design (registry read-backs only).
    """

    def __init__(self, capacity: int | None = None,
                 clock=time.monotonic):
        if capacity is None:
            from ..flags import FLAGS
            capacity = int(
                FLAGS.get("FLAGS_obs_timeseries_capacity") or 512)
        self.capacity = int(capacity)
        self._clock = clock
        self._lock = make_lock("TimeSeriesStore._lock")
        self._sources: dict[str, object] = {}       # name -> callable
        self._rates: list[tuple[str, str]] = []     # (series, of)
        self.series: dict[str, Series] = {}
        self.rules: list[AlertRule] = []
        self._firing: dict[str, dict] = {}
        self.ticks = 0
        self.samples = 0
        self.alerts_fired = 0
        self._sampler: threading.Thread | None = None
        self._sampler_stop = threading.Event()
        # optional fire-transition hook (DiagnosticCapture.attach):
        # called as on_fire(rule_name, info_dict) once per clear ->
        # firing edge, exception-fused.  None (the default) costs one
        # attribute test — the usual zero-overhead-off contract.
        self.on_fire = None

    # ------------------------------------------------------ registration
    def add_source(self, name: str, fn) -> Series:
        """Register a sampled callable; returning None skips a tick."""
        with self._lock:
            if name in self.series:
                raise ValueError(f"series {name!r} already registered")
            self._sources[name] = fn
            s = self.series[name] = Series(name, self.capacity)
        return s

    def add_metric(self, metric_name: str, series: str | None = None,
                   labels: dict | None = None) -> Series:
        """Sample a registry family (sum of its series, optionally
        label-filtered) under ``series`` (default: the metric name)."""
        return self.add_source(
            series or metric_name,
            lambda: metric_value(metric_name, labels))

    def add_rate(self, series: str, of: str) -> Series:
        """Derived series: per-second rate of ``of`` between its two
        newest samples — counters become sparkline-able throughputs
        (tok/s from serving_tokens_total)."""
        with self._lock:
            if series in self.series:
                raise ValueError(f"series {series!r} already registered")
            if of not in self.series:
                raise ValueError(f"base series {of!r} not registered")
            self._rates.append((series, of))
            s = self.series[series] = Series(series, self.capacity)
        return s

    def add_rule(self, rule: AlertRule) -> AlertRule:
        with self._lock:
            if any(r.name == rule.name for r in self.rules):
                raise ValueError(f"rule {rule.name!r} already registered")
            self.rules.append(rule)
        return rule

    # ------------------------------------------------------------- ticks
    def tick(self, now: float | None = None) -> int:
        """Sample every source, derive rate series, evaluate rules.
        Returns the number of points appended."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            sources = list(self._sources.items())
            rates = list(self._rates)
        appended = 0
        for name, fn in sources:
            try:
                v = fn()
            except Exception:
                v = None        # a broken source must not kill the tick
            if v is None:
                continue
            self.series[name].add(now, v)
            appended += 1
        for name, of in rates:
            base = self.series[of].points()
            if len(base) < 2:
                continue
            (t0, v0), (t1, v1) = base[-2], base[-1]
            if t1 > t0:
                self.series[name].add(now, (v1 - v0) / (t1 - t0))
                appended += 1
        with self._lock:
            self.ticks += 1
            self.samples += appended
        if appended:
            _M_SAMPLES.inc(appended)
        self._evaluate(now)
        return appended

    def _evaluate(self, now: float):
        for rule in self.rules:
            firing = rule.check(self, now)
            was = rule.name in self._firing
            if firing and not was:
                value = rule.measure(self, now)
                info = {"rule": rule.name, "series": rule.series,
                        "since": now, "value": value,
                        "condition": rule.describe()["condition"],
                        "help": rule.help}
                with self._lock:
                    self.alerts_fired += 1
                    self._firing[rule.name] = info
                _M_ALERTS.labels(rule.name).inc()
                _M_FIRING.labels(rule.name).set(1)
                flight_recorder().record(
                    "alert", "fire", rule=rule.name, series=rule.series,
                    value=value, threshold=rule.threshold)
                hook = self.on_fire
                if hook is not None:
                    try:
                        hook(rule.name, dict(info))
                    except Exception:
                        pass    # evidence capture must never break
                                # the alert evaluation that fired it
            elif firing and was:
                with self._lock:
                    self._firing[rule.name]["value"] = \
                        rule.measure(self, now)
            elif was and not firing:
                with self._lock:
                    del self._firing[rule.name]
                _M_FIRING.labels(rule.name).set(0)
                flight_recorder().record(
                    "alert", "clear", rule=rule.name, series=rule.series)

    # ----------------------------------------------------------- queries
    def firing(self) -> list:
        """Currently-firing alerts, ordered by rule name."""
        with self._lock:
            return [dict(self._firing[k])
                    for k in sorted(self._firing)]

    def windows(self, n: int | None = None) -> dict:
        """Recent ``[[t, value], ...]`` per series (newest last) — the
        compact history block of the /debug/fleet replica summary."""
        if n is None:
            from ..flags import FLAGS
            n = int(FLAGS.get("FLAGS_obs_fleet_window") or 32)
        out = {}
        for name in sorted(self.series):
            pts = self.series[name].points()[-int(n):]
            out[name] = [[round(t, 3), round(v, 6)] for t, v in pts]
        return out

    def state(self) -> dict:
        with self._lock:
            ticks, samples, fired = (self.ticks, self.samples,
                                     self.alerts_fired)
        return {"ticks": ticks, "samples": samples,
                "alerts_fired": fired,
                "series": sorted(self.series),
                "rules": [r.describe() for r in self.rules],
                "firing": self.firing()}

    # ----------------------------------------------------------- sampler
    def start_sampling(self, interval_s: float) -> "TimeSeriesStore":
        """Spawn the production tick driver (daemon thread).  A non-
        positive interval is a no-op, mirroring the watchdog."""
        if interval_s is None or float(interval_s) <= 0 \
                or self._sampler is not None:
            return self
        interval_s = float(interval_s)

        def loop():
            while not self._sampler_stop.wait(interval_s):
                self.tick()

        self._sampler = threading.Thread(
            target=loop, name="obs-sampler", daemon=True)
        self._sampler.start()
        return self

    def stop(self):
        self._sampler_stop.set()
        if self._sampler is not None:
            self._sampler.join(timeout=5.0)
            self._sampler = None
        self._sampler_stop = threading.Event()


def serving_sources(store: TimeSeriesStore) -> TimeSeriesStore:
    """Register the standard serving telemetry on ``store``: raw
    counters/gauges read back from the registry plus the derived
    signals the default alert rules and the dashboard consume (tok/s,
    prefix hit rate, max SLO burn rate)."""
    store.add_metric("serving_tokens_total", "tokens")
    store.add_metric("serving_decode_steps_total", "decode_steps")
    store.add_metric("serving_queue_depth", "queue_depth")
    store.add_metric("serving_active_slots", "active_slots")
    store.add_metric("serving_pages_free", "pages_free")
    store.add_metric("serving_pages_in_use", "pages_in_use")
    store.add_metric("serving_prefix_cached_pages", "cached_pages")
    store.add_metric("serving_page_fragmentation_ratio", "fragmentation")
    store.add_metric("serving_spec_acceptance_rate", "acceptance_rate")
    store.add_metric("serving_spec_tokens_total", "spec_proposed",
                     labels={"result": "proposed"})
    store.add_metric("serving_recovery_total", "recoveries")
    store.add_metric("serving_host_syncs_total", "host_syncs")
    store.add_rate("tok_s", of="tokens")

    def _prefix_hit_rate():
        hits = metric_value("serving_prefix_cache_pages_total",
                            {"result": "hit"})
        misses = metric_value("serving_prefix_cache_pages_total",
                              {"result": "miss"})
        if hits is None or misses is None or hits + misses == 0:
            return None
        return hits / (hits + misses)

    store.add_source("prefix_hit_rate", _prefix_hit_rate)

    def _burn_rate_max():
        m = default_registry().get("serving_slo_burn_rate")
        if m is None:
            return None
        values = [child.value for _, child in m._series()]
        return max(values) if values else None

    store.add_source("burn_rate_max", _burn_rate_max)
    return store


def default_rules(shed_burn_rate: float | None = None,
                  window_s: float = 30.0) -> list:
    """The stock anomaly rules over :func:`serving_sources` series.
    ``shed_burn_rate`` defaults to ``FLAGS_serving_shed_burn_rate``
    (falling back to burn rate 1.0 — budget consumed exactly at the
    objective's limit — when shedding is off)."""
    if shed_burn_rate is None:
        from ..flags import FLAGS
        shed_burn_rate = float(
            FLAGS.get("FLAGS_serving_shed_burn_rate") or 0.0)
    return [
        AlertRule("tok_s_collapse", "tokens", kind="rate", below=0.5,
                  window_s=window_s, min_samples=3,
                  when=("active_slots", ">", 0),
                  help_="decode throughput collapsed while slots were "
                        "active (stall / livelock signal)"),
        AlertRule("fragmentation_climb", "fragmentation", kind="rate",
                  above=0.02, window_s=window_s, min_samples=3,
                  help_="pool fragmentation climbing: the queue head "
                        "is losing placeable pages"),
        AlertRule("acceptance_drop", "acceptance_rate", below=0.2,
                  min_samples=2, when=("spec_proposed", ">", 0),
                  help_="speculative acceptance collapsed — drafts are "
                        "being paid for and thrown away"),
        AlertRule("burn_rate_breach", "burn_rate_max",
                  above=(shed_burn_rate or 1.0), min_samples=1,
                  help_="an SLO dimension is burning error budget at/"
                        "over the shed line"),
        AlertRule("recovery_surge", "recoveries", kind="rate",
                  above=0.0, window_s=window_s, min_samples=2,
                  help_="self-healing events (quarantine/rebuild/"
                        "stall) within the rate window"),
    ]
