"""Unified runtime telemetry (reference analogs: paddle/fluid/platform/
monitor.h StatRegistry, profiler chrome-trace counter events, and the
CommTaskManager hang diagnostics).

One process-wide :class:`MetricsRegistry` owns every runtime metric:

  * the eager dispatch cache (ops/registry.py) — hit / miss / eviction /
    uncacheable counters and a **retrace log** of (op, abstract input
    signature) for every cache miss, the jax recompilation-visibility
    pain point;
  * collectives (distributed/collective.py) — per-collective payload
    bytes + call counts, watchdog hang gauges;
  * hapi training (hapi/callbacks.MetricsLogger) — step wall time,
    samples/sec, device memory, host RSS.

Export: ``dump()`` writes Prometheus text + JSON (+ the retrace log)
into ``FLAGS_metrics_dir``; ``tools/metrics_report.py`` pretty-prints a
dump.  While a profiler records, counter changes are sampled on the
same perf_counter clock as RecordEvent spans so
``profiler.export_host_trace`` can merge "C"-phase counter tracks into
the chrome trace.
"""
from __future__ import annotations

import json
import os
import time

from ..sanitizer import make_lock

from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, bucket_quantiles,
    default_registry, merge_series_buckets, quantile_from_buckets)
from .tracing import (  # noqa: F401
    FlightRecorder, Span, SpanContext, Tracer, flight_recorder,
    format_traceparent, parse_traceparent, tracer)
from .timeseries import (  # noqa: F401
    AlertRule, Series, TimeSeriesStore, default_rules, metric_value,
    serving_sources)
from .profiling import (  # noqa: F401
    SamplingProfiler, active_profiler, set_active_profiler)
from .capture import (  # noqa: F401
    DiagnosticCapture, active_capture, set_active_capture)
from .usage import (  # noqa: F401
    TenantTable, UsageMeter, active_usage, merge_usage, request_ledger,
    set_active_usage)
from .requestlog import (  # noqa: F401
    ExemplarStore, RequestLog, RequestTimeline, active_requestlog,
    merge_exemplars, set_active_requestlog)

__all__ = ["AlertRule", "Counter", "DiagnosticCapture",
           "ExemplarStore", "FlightRecorder", "Gauge",
           "Histogram", "MetricsRegistry", "RequestLog",
           "RequestTimeline", "ResourceTracker",
           "SamplingProfiler", "Series",
           "Span", "SpanContext", "TenantTable", "TimeSeriesStore",
           "Tracer", "UsageMeter",
           "active_capture", "active_profiler", "active_quant",
           "active_requestlog", "active_usage",
           "bucket_quantiles", "merge_series_buckets",
           "quantile_from_buckets",
           "default_registry", "default_rules", "counter", "gauge",
           "histogram", "metric_value", "retrace_log", "RetraceLog",
           "dump", "reset", "flight", "enable_event_sampling",
           "chrome_counter_events", "flight_recorder",
           "format_traceparent", "parse_traceparent",
           "merge_exemplars", "merge_usage", "request_ledger",
           "resource_tracker", "serving_sources",
           "active_lora", "set_active_lora",
           "set_active_capture", "set_active_profiler",
           "set_active_quant", "set_active_requestlog",
           "set_active_usage", "tracer"]

# the quantized-serving provider: dump() writes quant.json from its
# quant_snapshot() (last engine built wins, like the profiler/usage
# holders — but plain module state here, no dedicated subsystem module)
_active_quant = None


def set_active_quant(provider):
    global _active_quant
    _active_quant = provider


def active_quant():
    return _active_quant


# the multi-LoRA provider: dump() writes lora.json from its
# lora_snapshot() (same last-engine-wins contract as the quant holder)
_active_lora = None


def set_active_lora(provider):
    global _active_lora
    _active_lora = provider


def active_lora():
    return _active_lora


def counter(name, help_="", labelnames=()):
    return default_registry().counter(name, help_, labelnames)


def gauge(name, help_="", labelnames=()):
    return default_registry().gauge(name, help_, labelnames)


def histogram(name, help_="", labelnames=(), buckets=None):
    from .registry import DEFAULT_BUCKETS
    return default_registry().histogram(
        name, help_, labelnames, buckets=buckets or DEFAULT_BUCKETS)


def enable_event_sampling(on=True):
    default_registry().enable_event_sampling(on)


def chrome_counter_events(pid=None):
    return default_registry().chrome_counter_events(pid)


def flight(category, event, **attrs):
    """Record one engine flight-recorder event (bounded ring; see
    tracing.FlightRecorder).  Hot-path safe: one deque append."""
    flight_recorder().record(category, event, **attrs)


class RetraceLog:
    """Record of every eager-cache miss that built a new executable:
    op name + abstract input signature (shapes/dtypes/statics — never
    values).  The analog of jax's ``jax_log_compiles`` made queryable:
    a retrace storm (same op, ever-changing signatures) shows up as one
    op with many entries instead of a silently slow step."""

    MAX_ENTRIES = 10_000

    def __init__(self):
        self._lock = make_lock("RetraceLog._lock")
        self._entries: dict[tuple, dict] = {}
        self._dropped = 0

    def record(self, op: str, signature: str):
        key = (op, signature)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e["count"] += 1
                e["last_time"] = time.perf_counter()
                return
            if len(self._entries) >= self.MAX_ENTRIES:
                self._dropped += 1
                return
            self._entries[key] = {
                "op": op, "signature": signature, "count": 1,
                "first_time": time.perf_counter(),
                "last_time": time.perf_counter()}

    def entries(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._entries.values()]

    def by_op(self) -> dict[str, int]:
        """op -> number of distinct signatures (retrace-storm ranking)."""
        out: dict[str, int] = {}
        for e in self.entries():
            out[e["op"]] = out.get(e["op"], 0) + 1
        return out

    def clear(self):
        with self._lock:
            self._entries.clear()
            self._dropped = 0

    def __len__(self):
        with self._lock:
            return len(self._entries)


retrace_log = RetraceLog()


def reset():
    """Drop all metrics + retrace entries + spans + flight events +
    resource accounting (tests / between runs)."""
    default_registry().reset()
    retrace_log.clear()
    tracer().reset()
    flight_recorder().clear()
    resource_tracker().reset()
    set_active_profiler(None)
    set_active_capture(None)
    set_active_usage(None)
    set_active_quant(None)
    set_active_lora(None)
    set_active_requestlog(None)


def dump(dir_=None) -> str | None:
    """Write the registry as ``metrics.prom`` + ``metrics.json``, the
    retrace log as ``retraces.json``, the span ring as ``trace.json``
    (chrome://tracing-loadable, with a parallel ``spans`` list for
    programmatic consumers), the flight-recorder ring as
    ``flight.json``, and the resource tracker's snapshot as
    ``resources.json`` into ``dir_`` (default: ``FLAGS_metrics_dir``).
    When a continuous profiler / diagnostic capture / usage meter /
    quantized engine / LoRA-serving engine / request log is active,
    adds ``profile.json`` / ``captures.json`` / ``usage.json`` /
    ``quant.json`` / ``lora.json`` / ``exemplars.json``.  Returns the
    directory, or None when no directory is configured."""
    if dir_ is None:
        from ..flags import FLAGS
        dir_ = FLAGS.get("FLAGS_metrics_dir") or None
    if not dir_:
        return None
    os.makedirs(dir_, exist_ok=True)
    reg = default_registry()
    with open(os.path.join(dir_, "metrics.prom"), "w") as f:
        f.write(reg.to_prometheus())
    with open(os.path.join(dir_, "metrics.json"), "w") as f:
        f.write(reg.to_json(indent=2))
    with open(os.path.join(dir_, "retraces.json"), "w") as f:
        json.dump({"entries": retrace_log.entries(),
                   "by_op": retrace_log.by_op()}, f, indent=2)
    tr = tracer()
    with open(os.path.join(dir_, "trace.json"), "w") as f:
        json.dump({"traceEvents": (tr.chrome_events()
                                   + chrome_counter_events()),
                   "spans": [s.to_dict() for s in tr.spans()],
                   "recorded": tr.spans_recorded,
                   "dropped": tr.spans_dropped}, f, indent=2)
    fr = flight_recorder()
    with open(os.path.join(dir_, "flight.json"), "w") as f:
        json.dump({"capacity": fr.capacity, "events": fr.snapshot()},
                  f, indent=2)
    with open(os.path.join(dir_, "resources.json"), "w") as f:
        json.dump(resource_tracker().snapshot(), f, indent=2)
    # side-files new in PR 15 — written only when the subsystems are
    # live, so pre-profiling dumps keep their exact shape
    prof = active_profiler()
    if prof is not None:
        with open(os.path.join(dir_, "profile.json"), "w") as f:
            json.dump(prof.snapshot(), f, indent=2)
    cap = active_capture()
    if cap is not None:
        with open(os.path.join(dir_, "captures.json"), "w") as f:
            json.dump(cap.index(), f, indent=2)
    meter = active_usage()
    if meter is not None:
        with open(os.path.join(dir_, "usage.json"), "w") as f:
            json.dump(meter.snapshot(), f, indent=2)
    quant = active_quant()
    if quant is not None:
        with open(os.path.join(dir_, "quant.json"), "w") as f:
            json.dump(quant.quant_snapshot(), f, indent=2)
    lora = active_lora()
    if lora is not None:
        with open(os.path.join(dir_, "lora.json"), "w") as f:
            json.dump(lora.lora_snapshot(), f, indent=2)
    rlog = active_requestlog()
    if rlog is not None:
        with open(os.path.join(dir_, "exemplars.json"), "w") as f:
            json.dump(rlog.snapshot(), f, indent=2)
    return dir_


# imported last: resources.py reads `retrace_log` and the registry the
# lines above set up
from .resources import ResourceTracker, resource_tracker  # noqa: E402,F401
