"""Alert-triggered diagnostic capture: the flight-data recorder.

PR 12's alert rules *detect* anomalies (tok/s collapse, fragmentation
climb) but until now a fire only bumped a counter — the evidence that
explains the anomaly exists exactly at fire time and was thrown away.
:class:`DiagnosticCapture` hooks :class:`TimeSeriesStore` fire events
(``store.on_fire``) and snapshots a bounded bundle the moment a rule
transitions clear -> firing:

  * the profiler window (``SamplingProfiler.snapshot()``) when one is
    running — the phase-attributed hot stacks *during* the anomaly;
  * the flight-recorder ring — the engine/scheduler events leading up
    to it;
  * ``ResourceTracker.snapshot()`` — memory / goodput / pool state;
  * ``lock_wait_graph()`` — who holds and waits on every sanitized
    lock (empty with the sanitizer off);
  * the recent time-series windows — the sparkline history that fired.

Each bundle lands as ``capture_<n>.json`` in ``FLAGS_obs_capture_dir``
(default: ``FLAGS_metrics_dir``) and in a bounded in-memory ring that
``GET /debug/captures`` serves even with no directory configured.
Noisy rules are rate-limited (``min_interval_s`` per rule) and
retention is bounded (``max_captures`` — the oldest file is deleted),
so a flapping alert cannot fill a disk.  Everything read here follows
the watchdog-dump contract: own-lock or lock-free reads only, each
wrapped so a broken source degrades that field to None instead of
killing the alert evaluation that invoked us.

Tests drive ``on_alert`` directly with a fake clock; production wiring
is one line: ``DiagnosticCapture(...).attach(store)``.
"""
from __future__ import annotations

import json
import os
import time
from collections import deque

from ..sanitizer import make_lock
from .registry import default_registry
from .tracing import flight_recorder

__all__ = ["DiagnosticCapture", "active_capture", "set_active_capture"]

_M_CAPTURES = default_registry().counter(
    "obs_captures_total",
    "diagnostic bundles captured on alert fire transitions, by rule",
    ("rule",))
_M_RATE_LIMITED = default_registry().counter(
    "obs_captures_rate_limited_total",
    "alert fires skipped by the per-rule capture rate limit", ("rule",))


class DiagnosticCapture:
    """Bounded alert-evidence recorder over one process.

    ``profiler`` / ``store`` are optional: without a profiler the
    bundle's ``profile`` field is None; without a store there are no
    series windows (and nothing calls ``on_alert`` unless wired by
    hand).  ``clock`` feeds the rate limiter — monotonic in
    production, fake in tests.
    """

    def __init__(self, *, dir_=None, min_interval_s: float | None = None,
                 max_captures: int | None = None, profiler=None,
                 store=None, clock=time.monotonic):
        from ..flags import FLAGS
        if dir_ is None:
            dir_ = (FLAGS.get("FLAGS_obs_capture_dir")
                    or FLAGS.get("FLAGS_metrics_dir") or None)
        if min_interval_s is None:
            min_interval_s = float(
                FLAGS.get("FLAGS_obs_capture_min_interval_s") or 60.0)
        if max_captures is None:
            max_captures = int(FLAGS.get("FLAGS_obs_capture_max") or 8)
        self.dir = dir_ or None
        self.min_interval_s = float(min_interval_s)
        self.max_captures = max(int(max_captures), 1)
        self.profiler = profiler
        self.store = store
        self._clock = clock
        self._lock = make_lock("DiagnosticCapture._lock")
        self._last_fire: dict[str, float] = {}      # rule -> last t
        self._bundles: deque = deque(maxlen=self.max_captures)
        self._paths: deque = deque()                # retained files
        self.captures = 0                           # python mirror
        self.rate_limited = 0
        self.by_rule: dict[str, int] = {}

    # ------------------------------------------------------------ wiring
    def attach(self, store) -> "DiagnosticCapture":
        """Hook a TimeSeriesStore's fire events; returns self."""
        self.store = store
        store.on_fire = self.on_alert
        return self

    # ----------------------------------------------------------- capture
    def on_alert(self, rule: str, info: dict | None = None,
                 now: float | None = None) -> dict | None:
        """One fire transition.  Returns the bundle written, or None
        when the per-rule rate limit suppressed it.  Never raises:
        invoked from inside alert evaluation."""
        now = self._clock() if now is None else float(now)
        with self._lock:
            last = self._last_fire.get(rule)
            if last is not None and now - last < self.min_interval_s:
                self.rate_limited += 1
                limited = True
            else:
                self._last_fire[rule] = now
                self.captures += 1
                self.by_rule[rule] = self.by_rule.get(rule, 0) + 1
                n = self.captures
                limited = False
        if limited:
            _M_RATE_LIMITED.labels(rule).inc()
            return None
        bundle = self._bundle(rule, info, now, n)
        path = self._write(bundle, n)
        bundle["path"] = path
        with self._lock:
            self._bundles.append(bundle)
        _M_CAPTURES.labels(rule).inc()
        flight_recorder().record("capture", "write", rule=rule,
                                 capture=n, path=path)
        return bundle

    def _bundle(self, rule, info, now, n) -> dict:
        """Assemble the evidence.  Watchdog-dump contract: every source
        is individually fused — a broken one degrades to None."""
        try:
            profile = (self.profiler.snapshot()
                       if self.profiler is not None else None)
        except Exception:
            profile = None
        try:
            fr = flight_recorder()
            flight = {"capacity": fr.capacity, "events": fr.snapshot()}
        except Exception:
            flight = None
        try:
            from . import resource_tracker
            resources = resource_tracker().snapshot()
        except Exception:
            resources = None
        try:
            from ..sanitizer import lock_wait_graph
            lock_graph = lock_wait_graph()
        except Exception:
            lock_graph = None
        try:
            series = (self.store.windows()
                      if self.store is not None else None)
        except Exception:
            series = None
        return {"capture": n, "rule": rule, "alert": info,
                "captured_at": round(now, 6), "profile": profile,
                "flight": flight, "resources": resources,
                "lock_wait_graph": lock_graph, "series": series}

    def _write(self, bundle, n) -> str | None:
        if not self.dir:
            return None
        try:
            os.makedirs(self.dir, exist_ok=True)
            path = os.path.join(self.dir, f"capture_{n}.json")
            with open(path, "w") as f:
                json.dump(bundle, f, indent=2)
        except (OSError, TypeError, ValueError):
            return None
        with self._lock:
            self._paths.append(path)
            evict = (self._paths.popleft()
                     if len(self._paths) > self.max_captures else None)
        if evict is not None:
            try:
                os.remove(evict)
            except OSError:
                pass
        return path

    # ----------------------------------------------------------- queries
    def index(self) -> dict:
        """The ``GET /debug/captures`` payload: counts + the retained
        bundle headlines (full bundles stay on disk / in recent())."""
        with self._lock:
            retained = [{"capture": b["capture"], "rule": b["rule"],
                         "captured_at": b["captured_at"],
                         "path": b.get("path")}
                        for b in self._bundles]
            return {"captures": self.captures,
                    "rate_limited": self.rate_limited,
                    "by_rule": dict(self.by_rule),
                    "min_interval_s": self.min_interval_s,
                    "max_captures": self.max_captures,
                    "dir": self.dir, "retained": retained}

    def recent(self, n: int | None = None) -> list[dict]:
        with self._lock:
            out = list(self._bundles)
        return out if n is None else out[-int(n):]


# process-wide capture recorder (installed by the serving server so
# observability.dump() can write captures.json next to the other
# artifacts)
_ACTIVE: DiagnosticCapture | None = None


def active_capture() -> DiagnosticCapture | None:
    return _ACTIVE


def set_active_capture(capture: DiagnosticCapture | None):
    global _ACTIVE
    _ACTIVE = capture
    return capture
