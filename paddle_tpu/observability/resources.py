"""Resource observatory: where HBM, compile time, and tokens go.

Reference analogs: Paddle's ``paddle.device.cuda.memory_*`` stats
surface + the profiler's compile/kernel accounting, joined with the
serving-era efficiency reporting (Orca/vLLM goodput and KV-utilization
numbers).  PRs 1 and 5 answered *what happened* (metrics) and
*when/why* (traces, SLO); this layer answers *what did it cost*:

  * **memory** — per-device ``memory_stats()`` samples (bytes-in-use /
    peak) plus host RSS, degrading cleanly on backends that export no
    stats (CPU): the sample records what exists and never raises;
  * **compile ledger** — per-jit compile count, estimated compile
    seconds, and the arg-shape signature of the trace that caused it.
    The serving engine feeds it first-call timings of its jits (decode
    step, per-bucket prefills, CoW copy); the eager dispatch cache's
    retrace log (``observability.retrace_log``) is merged into every
    snapshot so one report covers both compilation surfaces;
  * **goodput** — useful generated tokens (requests that finished
    ``length``/``eos``) vs tokens thrown away (``cancelled`` /
    ``deadline`` / eviction / preemption): wasted decode work is real
    HBM-seconds, and its fraction is the serving-efficiency headline;
  * **throughput / MFU** — tokens/s over the engine's measured phase
    seconds, and a model-FLOPs-utilization estimate
    ``tokens_per_s * 2 * n_params / peak_flops`` (decode is ~2 FLOPs
    per parameter per token).  Peak FLOPs comes from
    ``FLAGS_resource_peak_tflops`` when set, else a device-kind table;
    unknown devices (CPU) report ``mfu: null`` instead of a lie.

One process-wide tracker (``resource_tracker()``, mirroring the metrics
registry design): ``snapshot()`` is the single JSON payload served by
``GET /debug/resources``, embedded in watchdog hang dumps, and written
to ``resources.json`` by ``observability.dump()``.  Every method is
safe to call from the watchdog thread: the tracker takes only its own
lock, never an engine lock.
"""
from __future__ import annotations

import time

from ..sanitizer import make_lock
from .registry import default_registry

__all__ = ["CompileLedger", "ResourceTracker", "resource_tracker"]

# bf16 peak FLOP/s per chip by device kind (public figures; the serving
# MFU denominator — FLAGS_resource_peak_tflops overrides)
_PEAK_TFLOPS = {
    "TPU v5p": 459.0, "TPU v5 lite": 197.0, "TPU v5e": 197.0,
    "TPU v6 lite": 918.0, "TPU v6e": 918.0, "TPU v4": 275.0,
    "TPU v3": 123.0, "TPU v2": 45.0,
}

# useful = the request's tokens were delivered as a completed answer;
# wasted = decode work thrown away (client cancel, missed deadline,
# scheduler eviction/preemption)
_USEFUL_REASONS = ("length", "eos")


def _peak_flops(device_kind: str | None) -> float | None:
    from ..flags import FLAGS
    override = float(FLAGS.get("FLAGS_resource_peak_tflops") or 0.0)
    if override > 0:
        return override * 1e12
    if not device_kind:
        return None
    for k, v in _PEAK_TFLOPS.items():
        if device_kind.lower().startswith(k.lower()):
            return v * 1e12
    return None


def _host_rss_bytes() -> int:
    """Current host RSS (linux /proc; fallback: peak RSS from
    getrusage) — same probe hapi.MetricsLogger uses."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:
        return 0


class CompileLedger:
    """Per-jit compile accounting: how many times each jitted program
    traced, the estimated seconds those traces cost, and the arg-shape
    signature of the latest trace.

    The engine has no portable compile hook, so "compile seconds" is
    the wall time of the first call after a fresh trace was detected
    (execution rides along — an upper bound, which is the honest
    direction for a cost ledger)."""

    def __init__(self):
        self._lock = make_lock("CompileLedger._lock")
        self._jits: dict[str, dict] = {}

    def record(self, jit: str, seconds: float, signature: str = ""):
        c = _compile_metrics()
        c["compiles"].labels(jit).inc()
        c["seconds"].labels(jit).inc(max(float(seconds), 0.0))
        with self._lock:
            e = self._jits.setdefault(
                jit, {"count": 0, "seconds": 0.0, "signatures": []})
            e["count"] += 1
            e["seconds"] += max(float(seconds), 0.0)
            if signature and signature not in e["signatures"]:
                e["signatures"].append(signature)
                del e["signatures"][:-8]     # keep the newest few

    def snapshot(self) -> dict:
        with self._lock:
            jits = {k: {"count": v["count"],
                        "seconds": round(v["seconds"], 6),
                        "signatures": list(v["signatures"])}
                    for k, v in self._jits.items()}
        return {"jits": jits,
                "total_compiles": sum(v["count"] for v in jits.values()),
                "total_seconds": round(sum(v["seconds"]
                                           for v in jits.values()), 6)}

    def reset(self):
        with self._lock:
            self._jits.clear()


def _compile_metrics():
    reg = default_registry()
    return {
        "compiles": reg.counter(
            "obs_jit_compiles_total",
            "jit traces recorded in the compile ledger", ("jit",)),
        "seconds": reg.counter(
            "obs_jit_compile_seconds_total",
            "estimated wall seconds spent tracing+compiling, by jit "
            "(first-call timing — execution rides along)", ("jit",)),
    }


def _goodput_metrics():
    reg = default_registry()
    return {
        "tokens": reg.counter(
            "serving_goodput_tokens_total",
            "generated tokens by usefulness: 'useful' reached the "
            "client as a completed answer (length/eos), 'wasted' was "
            "thrown away (cancel/deadline/eviction)", ("kind",)),
        "ratio": reg.gauge(
            "serving_goodput_ratio",
            "useful / (useful + wasted) generated tokens"),
    }


def _memory_metrics():
    # EXACT signatures of the gauges hapi.MetricsLogger registers —
    # _get_or_create returns the same families, so serving and training
    # memory samples land on one timeline
    reg = default_registry()
    return {
        "mem": reg.gauge("device_bytes_in_use", "live device memory",
                         ("device",)),
        "peak": reg.gauge("device_peak_bytes_in_use",
                          "peak device memory", ("device",)),
        "rss": reg.gauge("host_rss_bytes", "host process RSS"),
    }


class ResourceTracker:
    """Process-wide memory / compile / goodput / throughput accounting
    (see module docstring).  All mutators take only the tracker's own
    lock — watchdog-safe by construction."""

    def __init__(self):
        self._lock = make_lock("ResourceTracker._lock")
        self.compiles = CompileLedger()
        self._reset_state()

    def _reset_state(self):
        with self._lock:
            self._devices: dict[str, dict] = {}
            self._rss = 0
            self._mem_samples = 0
            self._useful = 0
            self._wasted = 0
            self._finishes: dict[str, int] = {}
            self._tokens = 0
            self._phase_s: dict[str, float] = {}
            self._n_params = 0
            self._device_kind: str | None = None
            self._mesh: dict[str, dict] = {}

    # ----------------------------------------------------------- feeding
    def set_model(self, *, n_params: int, device_kind: str | None):
        with self._lock:
            self._n_params = int(n_params)
            self._device_kind = device_kind

    def set_mesh(self, positions: dict[str, dict]):
        """Register the serving mesh layout: device key ("platform:id",
        matching :meth:`sample_memory`'s keys) -> axis-position dict
        (e.g. ``{"tp": 2}``).  Memory samples and snapshots annotate
        those devices with their mesh position, and every mesh device
        appears in the memory section even when its backend exports no
        ``memory_stats()`` (CPU) — per-device coverage is the point."""
        with self._lock:
            self._mesh = {str(k): dict(v) for k, v in positions.items()}

    def note_phase(self, phase: str, seconds: float):
        """Accumulate engine wall time by phase (prefill / decode /
        host_sync) — the tokens/s and MFU denominator."""
        with self._lock:
            self._phase_s[phase] = self._phase_s.get(phase, 0.0) \
                + max(float(seconds), 0.0)

    def note_tokens(self, n: int = 1):
        with self._lock:
            self._tokens += int(n)

    def note_finish(self, reason: str, generated: int):
        """One finished request: its generated tokens count as useful
        (length/eos) or wasted (cancelled/deadline/evicted)."""
        generated = int(generated)
        with self._lock:
            self._finishes[reason] = self._finishes.get(reason, 0) + 1
            if reason in _USEFUL_REASONS:
                self._useful += generated
            else:
                self._wasted += generated
            useful, wasted = self._useful, self._wasted
        g = _goodput_metrics()
        if generated:
            g["tokens"].labels(
                "useful" if reason in _USEFUL_REASONS else "wasted"
            ).inc(generated)
        if useful + wasted:
            g["ratio"].set(useful / (useful + wasted))

    def sample_memory(self):
        """One memory poll: per-device ``memory_stats()`` (clean no-op
        for backends without them — CPU) + host RSS.  Never raises."""
        devices: dict[str, dict] = {}
        try:
            import jax
            for d in jax.devices():
                stats = getattr(d, "memory_stats", lambda: {})() or {}
                entry = {}
                if "bytes_in_use" in stats:
                    entry["bytes_in_use"] = int(stats["bytes_in_use"])
                if "peak_bytes_in_use" in stats:
                    entry["peak_bytes_in_use"] = int(
                        stats["peak_bytes_in_use"])
                if entry:
                    devices[f"{d.platform}:{d.id}"] = entry
        except Exception:
            devices = {}
        rss = _host_rss_bytes()
        m = _memory_metrics()
        for key, entry in devices.items():
            if "bytes_in_use" in entry:
                m["mem"].labels(key).set(entry["bytes_in_use"])
            if "peak_bytes_in_use" in entry:
                m["peak"].labels(key).set(entry["peak_bytes_in_use"])
        if rss:
            m["rss"].set(rss)
        with self._lock:
            # mesh devices always appear, stats or not (CPU backends
            # export none); positions annotate whatever was sampled
            for key, pos in self._mesh.items():
                entry = devices.setdefault(key, {})
                entry["mesh"] = dict(pos)
            self._devices = devices
            self._rss = rss
            self._mem_samples += 1

    # ---------------------------------------------------------- snapshot
    def snapshot(self) -> dict:
        """The resources.json / /debug/resources / watchdog payload.
        Reads only tracker state, the metrics registry, and the eager
        retrace log — safe while an engine is wedged."""
        with self._lock:
            devices = {k: dict(v) for k, v in self._devices.items()}
            # mesh registration shows up even before the first memory
            # poll — /debug/resources must cover every mesh device
            for key, pos in self._mesh.items():
                devices.setdefault(key, {})["mesh"] = dict(pos)
            rss, samples = self._rss, self._mem_samples
            useful, wasted = self._useful, self._wasted
            finishes = dict(self._finishes)
            tokens = self._tokens
            phase_s = dict(self._phase_s)
            n_params = self._n_params
            kind = self._device_kind
        compiles = self.compiles.snapshot()
        compiles["eager_by_op"] = _eager_retraces()
        total = useful + wasted
        busy = sum(phase_s.values())
        tps = tokens / busy if busy > 0 else 0.0
        peak = _peak_flops(kind)
        mfu = (tps * 2.0 * n_params / peak
               if peak and n_params else None)
        return {
            "memory": {"devices": devices, "host_rss_bytes": rss,
                       "samples": samples},
            "compiles": compiles,
            "goodput": {
                "useful_tokens": useful, "wasted_tokens": wasted,
                "ratio": (useful / total) if total else None,
                "finishes": finishes},
            "throughput": {
                "tokens": tokens,
                "phase_seconds": {k: round(v, 6)
                                  for k, v in phase_s.items()},
                "tokens_per_s": round(tps, 3),
                "n_params": n_params, "device_kind": kind,
                "peak_flops": peak,
                "mfu": (round(mfu, 6) if mfu is not None else None)},
            "pool": _pool_from_registry(),
        }

    def reset(self):
        self.compiles.reset()
        self._reset_state()


def _eager_retraces() -> dict:
    """op -> distinct-signature count from the eager dispatch cache's
    retrace log (the other compilation surface)."""
    try:
        from . import retrace_log
        return retrace_log.by_op()
    except Exception:
        return {}


def _pool_from_registry() -> dict:
    """Read back the block manager's page-pool gauges — the tracker
    never touches engine structures, so this stays watchdog-safe."""
    reg = default_registry()
    out = {}
    for key, name in (("in_use", "serving_pages_in_use"),
                      ("free", "serving_pages_free"),
                      ("cached", "serving_prefix_cached_pages"),
                      ("total", "serving_pages_total"),
                      ("fragmentation_ratio",
                       "serving_page_fragmentation_ratio")):
        m = reg.get(name)
        if m is not None and not m.labelnames:
            out[key] = m.value
    return out


_tracker = ResourceTracker()


def resource_tracker() -> ResourceTracker:
    return _tracker


def record_compile(jit: str, t0: float, signature: str = ""):
    """Convenience for first-call jit timing: ``t0`` is the
    perf_counter stamp taken before the call that traced."""
    _tracker.compiles.record(jit, time.perf_counter() - t0, signature)
