"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

Reference analog: the profiler's chrome-trace counter events and the
C++ monitor registry (paddle/fluid/platform/monitor.h — StatRegistry of
named int64 stats exported in bulk).  Here one registry owns every
runtime metric (eager-cache hits, collective bytes, hapi step timings…)
and exports them as Prometheus text or JSON; the dump directory is
driven by ``FLAGS_metrics_dir`` (flags.py).

Design constraints:
  * hot-path friendly — a bound child (``counter.labels(...)`` result,
    or the unlabeled default child) increments under one small lock;
    sub-microsecond, invisible next to a jitted dispatch.
  * optional event sampling — while a Profiler records, every counter
    and gauge change also appends a (perf_counter, name, value) sample
    so the chrome trace can carry "C"-phase counter tracks on the same
    clock as the host spans (profiler.export_host_trace merges them).
"""
from __future__ import annotations

import json
import os
import time

from ..sanitizer import make_lock
from .quantiles import (  # noqa: F401  (re-export: one canonical impl)
    bucket_quantiles, merge_series_buckets, quantile_from_buckets)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "bucket_quantiles", "default_registry",
           "merge_series_buckets", "quantile_from_buckets",
           "SERVING_LATENCY_BUCKETS"]

# Prometheus-conventional default buckets (seconds-scale latencies).
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Serving-latency buckets (TTFT / per-output-token): finer sub-ms floor
# than DEFAULT_BUCKETS — a decode step is tens of µs on-chip — while the
# tail still resolves multi-second queueing delays.
SERVING_LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)


def _fmt_labels(labelnames, labelvalues):
    if not labelnames:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"'
                     for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


def _escape(v):
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


def _escape_help(v):
    # HELP docstrings escape only backslash and newline (the text
    # exposition format; quotes stay literal there)
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


class _Child:
    """One (metric, labelvalues) time series."""

    __slots__ = ("_metric", "_labelvalues", "_lock", "_value")

    def __init__(self, metric, labelvalues):
        self._metric = metric
        self._labelvalues = labelvalues
        self._lock = make_lock(f"{metric.name}.child")
        self._value = 0.0

    @property
    def value(self):
        with self._lock:
            return self._value

    def _sample(self):
        reg = self._metric._registry
        if reg is not None and reg._sampling:
            reg._record_event(self._metric.name, self._labelvalues,
                              self._value)

    def reset(self):
        with self._lock:
            self._value = 0.0


class _CounterChild(_Child):
    def inc(self, n=1.0):
        if n < 0:
            raise ValueError("counters only go up; use a Gauge")
        with self._lock:
            self._value += n
        self._sample()


class _GaugeChild(_Child):
    def set(self, v):
        with self._lock:
            self._value = float(v)
        self._sample()

    def inc(self, n=1.0):
        with self._lock:
            self._value += n
        self._sample()

    def dec(self, n=1.0):
        self.inc(-n)


class _HistogramChild(_Child):
    __slots__ = ("_buckets", "_counts", "_sum", "_count")

    def __init__(self, metric, labelvalues):
        super().__init__(metric, labelvalues)
        self._buckets = metric.buckets
        self._counts = [0] * (len(self._buckets) + 1)   # +inf tail
        self._sum = 0.0
        self._count = 0

    def observe(self, v):
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, le in enumerate(self._buckets):
                if v <= le:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    @property
    def count(self):
        with self._lock:
            return self._count

    @property
    def sum(self):
        with self._lock:
            return self._sum

    def reset(self):
        with self._lock:
            self._counts = [0] * (len(self._buckets) + 1)
            self._sum = 0.0
            self._count = 0

    def snapshot(self):
        """Cumulative (le, count) pairs + sum/count, prometheus-style."""
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        cum, acc = [], 0
        for le, c in zip(list(self._buckets) + ["+Inf"], counts):
            acc += c
            cum.append((le, acc))
        return {"buckets": cum, "sum": s, "count": total}

    def quantile(self, q):
        """Bucket-quantile estimate (upper bucket edge crossing the
        q-rank; see quantiles.quantile_from_buckets).  None when empty,
        ``"+Inf"`` when the rank lands in the overflow bucket."""
        snap = self.snapshot()
        return quantile_from_buckets(snap["buckets"], snap["count"], q)

    def quantiles(self, qs=(0.5, 0.95, 0.99)):
        snap = self.snapshot()
        return bucket_quantiles(snap["buckets"], snap["count"], qs)


class _Metric:
    """A named metric family; children are one per labelvalues tuple."""

    child_cls = _Child
    kind = "untyped"

    def __init__(self, name, help_="", labelnames=(), registry=None):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._registry = registry
        self._children: dict[tuple, _Child] = {}
        self._lock = make_lock(f"{name}.metric")
        if not self.labelnames:
            # pre-bind the unlabeled series so bare .inc()/.set() is one
            # attribute hop, no dict lookup on the hot path
            self._default = self._get_child(())
        else:
            self._default = None

    def labels(self, *labelvalues, **labelkw):
        if labelkw:
            if labelvalues:
                raise ValueError("pass labels positionally or by keyword, "
                                 "not both")
            labelvalues = tuple(labelkw[k] for k in self.labelnames)
        labelvalues = tuple(str(v) for v in labelvalues)
        if len(labelvalues) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name!r} expects labels {self.labelnames}, "
                f"got {labelvalues}")
        return self._get_child(labelvalues)

    def _get_child(self, labelvalues):
        c = self._children.get(labelvalues)
        if c is None:
            with self._lock:
                c = self._children.setdefault(
                    labelvalues, self.child_cls(self, labelvalues))
        return c

    def _series(self):
        return list(self._children.items())

    def fold_label(self, labelname, value, into):
        """Bounded-cardinality eviction: move every child series whose
        ``labelname`` equals ``value`` into the series with that label
        replaced by ``into`` (values summed, originals dropped), so the
        family's grand total is preserved while the evicted label value
        disappears from the scrape.  Returns the number of series
        folded; a no-op when the family has no such label."""
        if labelname not in self.labelnames:
            return 0
        idx = self.labelnames.index(labelname)
        value, into = str(value), str(into)
        if value == into:
            return 0
        with self._lock:
            doomed = [lv for lv in self._children if lv[idx] == value]
            for lv in doomed:
                child = self._children.pop(lv)
                dest_lv = lv[:idx] + (into,) + lv[idx + 1:]
                dest = self._children.get(dest_lv)
                if dest is None:
                    dest = self.child_cls(self, dest_lv)
                    self._children[dest_lv] = dest
                self._fold_child(child, dest)
        return len(doomed)

    @staticmethod
    def _fold_child(child, dest):
        with child._lock:
            v = child._value
        with dest._lock:
            dest._value += v

    # delegate the unlabeled fast path
    def __getattr__(self, item):
        if item in ("inc", "dec", "set", "observe", "value", "count",
                    "sum", "snapshot", "quantile", "quantiles"):
            d = self.__dict__.get("_default")
            if d is None:
                raise ValueError(
                    f"metric {self.name!r} has labels {self.labelnames}; "
                    f"bind them with .labels(...) first")
            return getattr(d, item)
        raise AttributeError(item)


class Counter(_Metric):
    child_cls = _CounterChild
    kind = "counter"


class Gauge(_Metric):
    child_cls = _GaugeChild
    kind = "gauge"


class Histogram(_Metric):
    child_cls = _HistogramChild
    kind = "histogram"

    def __init__(self, name, help_="", labelnames=(), registry=None,
                 buckets=DEFAULT_BUCKETS):
        self.buckets = tuple(sorted(float(b) for b in buckets))
        super().__init__(name, help_, labelnames, registry)

    @staticmethod
    def _fold_child(child, dest):
        with child._lock:
            counts, s, n = list(child._counts), child._sum, child._count
        with dest._lock:
            for i, c in enumerate(counts):
                dest._counts[i] += c
            dest._sum += s
            dest._count += n


_METRIC_CLS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricsRegistry:
    """Get-or-create metric families + bulk export (prometheus / JSON) +
    sampled counter events for the chrome trace."""

    MAX_EVENTS = 100_000          # sampling ring bound

    def __init__(self):
        self._metrics: dict[str, _Metric] = {}
        self._lock = make_lock("Registry._lock")
        self._sampling = False
        self._events: list[tuple[float, str, tuple, float]] = []
        self._events_lock = make_lock("Registry._events_lock")

    # ------------------------------------------------------- constructors
    def _get_or_create(self, kind, name, help_, labelnames, **kw):
        m = self._metrics.get(name)
        if m is not None:
            if m.kind != kind or m.labelnames != tuple(labelnames):
                raise ValueError(
                    f"metric {name!r} already registered as {m.kind} "
                    f"with labels {m.labelnames}")
            return m
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = _METRIC_CLS[kind](name, help_, labelnames,
                                      registry=self, **kw)
                self._metrics[name] = m
            return m

    def counter(self, name, help_="", labelnames=()) -> Counter:
        return self._get_or_create("counter", name, help_, labelnames)

    def gauge(self, name, help_="", labelnames=()) -> Gauge:
        return self._get_or_create("gauge", name, help_, labelnames)

    def histogram(self, name, help_="", labelnames=(),
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create("histogram", name, help_, labelnames,
                                   buckets=buckets)

    def get(self, name):
        return self._metrics.get(name)

    def reset(self):
        """Zero every series in place and drop sampled events.  Families
        stay registered — modules hold pre-bound children (e.g. the
        eager-cache counters in ops/registry.py), so dropping them would
        orphan those hot-path handles."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for _, child in m._series():
                child.reset()
        with self._events_lock:
            self._events.clear()

    # --------------------------------------------------- counter sampling
    def enable_event_sampling(self, on=True):
        self._sampling = bool(on)

    def _record_event(self, name, labelvalues, value):
        with self._events_lock:
            if len(self._events) < self.MAX_EVENTS:
                self._events.append(
                    (time.perf_counter(), name, labelvalues, value))

    def chrome_counter_events(self, pid=None):
        """Sampled metric changes as chrome-trace 'C' (counter) phase
        events, on the perf_counter clock RecordEvent spans use."""
        pid = os.getpid() if pid is None else pid
        with self._events_lock:
            events = list(self._events)
        out = []
        for t, name, labelvalues, value in events:
            series = name + _fmt_labels(
                self._metrics[name].labelnames
                if name in self._metrics else (), labelvalues)
            out.append({"name": series, "ph": "C", "ts": t * 1e6,
                        "pid": pid, "tid": 0, "args": {"value": value}})
        return out

    # ------------------------------------------------------------ export
    def to_prometheus(self) -> str:
        """Prometheus text exposition (format version 0.0.4).
        Conformance contract (tested by the text-format lint in
        tests/test_tracing.py): exactly one ``# HELP`` then one
        ``# TYPE`` line per family, in that order, before its samples;
        every histogram series exports a ``+Inf`` bucket whose
        cumulative count equals ``_count``, and both ``_sum`` and
        ``_count`` are present.  Serve with
        ``Content-Type: text/plain; version=0.0.4``."""
        lines = []
        for name, m in sorted(self._metrics.items()):
            lines.append(f"# HELP {name} {_escape_help(m.help)}".rstrip())
            lines.append(f"# TYPE {name} {m.kind}")
            for labelvalues, child in sorted(m._series()):
                lbl = _fmt_labels(m.labelnames, labelvalues)
                if m.kind == "histogram":
                    snap = child.snapshot()
                    for le, c in snap["buckets"]:
                        le_s = "+Inf" if le == "+Inf" else repr(le)
                        extra = (lbl[1:-1] + "," if lbl else "")
                        lines.append(
                            f'{name}_bucket{{{extra}le="{le_s}"}} {c}')
                    lines.append(f"{name}_sum{lbl} {snap['sum']}")
                    lines.append(f"{name}_count{lbl} {snap['count']}")
                else:
                    lines.append(f"{name}{lbl} {child.value}")
        return "\n".join(lines) + "\n"

    def to_dict(self) -> dict:
        out = {}
        for name, m in sorted(self._metrics.items()):
            series = []
            for labelvalues, child in sorted(m._series()):
                entry = {"labels": dict(zip(m.labelnames, labelvalues))}
                if m.kind == "histogram":
                    snap = child.snapshot()
                    entry["buckets"] = [[le, c] for le, c
                                        in snap["buckets"]]
                    entry["sum"] = snap["sum"]
                    entry["count"] = snap["count"]
                else:
                    entry["value"] = child.value
                series.append(entry)
            out[name] = {"type": m.kind, "help": m.help, "series": series}
        return out

    def to_json(self, indent=None) -> str:
        return json.dumps(self.to_dict(), indent=indent)


_default = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    return _default
