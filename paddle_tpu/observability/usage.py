"""Per-request cost attribution + tenant usage metering.

Reference analog: the per-user accounting planes of PAPER.md's fleet
(multi-tenant serving where "what did tenant Y cost" is a first-class
query, not a log-scrape).  Every resource the serving engine consumes
is already counted *globally* (goodput tokens, pages allocated, spill
bytes); this module attributes them to the request and tenant that
consumed them:

  * scalar costs (queue seconds, prefill computed/cached token split,
    chunk counts, decode tokens, speculation proposed/accepted,
    spill/restore pages+bytes, preemptions, replays) accrue on the
    :class:`~paddle_tpu.serving.request.Request` itself — plain int
    adds at the seams that already update the global mirrors, so the
    per-request ledger sums to the global counters *exactly* on
    deterministic workloads;
  * **KV page-seconds** — pages held × residency, integrated on the
    engine clock — are tracked here: the meter keeps a page → holders
    map fed by BlockManager hold/release hooks and charges each holder
    ``1/|holders|`` per shared page, so the conservation law

        sum over tenants of page_seconds == integral of live-pages dt

    holds identically (each live page contributes exactly 1 to the
    summed rate at every instant).  A separate host-tier track charges
    parked spill pages (content-addressed digests) to the tenant that
    parked them, across preempt -> spill -> resume;
  * a **tenant dimension** with bounded label cardinality: requests
    carry a tenant id (default ``"anon"``); the LRU
    :class:`TenantTable` caps distinct tenants, folding the
    least-recently-seen tenant's aggregates — python rows *and* its
    per-tenant metric series (:meth:`registry fold_label
    <paddle_tpu.observability.registry._Metric.fold_label>`) — into a
    reserved ``"(evicted)"`` rollup, so a hostile client cycling
    tenant ids cannot explode the metrics registry and fleet totals
    still conserve.

Zero-overhead-off contract (same as profiling / fault injection): with
``FLAGS_serving_usage_meter`` unset no meter object exists and every
serving-path call site is a single ``is not None`` test (pinned by the
perf_gate ``usage_meter`` scenario).
"""
from __future__ import annotations

import time
from collections import OrderedDict

from ..sanitizer import make_lock
from .registry import default_registry

__all__ = ["UsageMeter", "TenantTable", "EVICTED_TENANT",
           "request_ledger", "merge_usage", "active_usage",
           "set_active_usage"]

_REG = default_registry()

_M_TOKENS = _REG.counter(
    "serving_usage_tokens_total",
    "tokens attributed per tenant, split by kind (prefill_computed / "
    "prefill_cached / decode)", ("tenant", "kind"))
_M_REQS = _REG.counter(
    "serving_usage_requests_total",
    "finished requests attributed per tenant, by finish reason",
    ("tenant", "reason"))
_M_PAGE_SECONDS = _REG.counter(
    "serving_usage_page_seconds_total",
    "KV page-seconds (pages held x residency on the engine clock) "
    "attributed per tenant, by tier (device / host spill)",
    ("tenant", "tier"))
_M_QUEUE_SECONDS = _REG.counter(
    "serving_usage_queue_seconds_total",
    "queue-wait seconds attributed per tenant (admission + resume "
    "re-queues)", ("tenant",))
_M_SPILL_BYTES = _REG.counter(
    "serving_usage_spill_bytes_total",
    "preemption spill bytes attributed to the preempted tenant",
    ("tenant",))
_M_PREEMPT = _REG.counter(
    "serving_usage_preemptions_total",
    "preemptions suffered, attributed to the preempted tenant",
    ("tenant",))
_M_SLO = _REG.counter(
    "serving_usage_slo_total",
    "per-tenant SLO verdicts mirrored from the SLOTracker "
    "(dimension x good/violation)", ("tenant", "dimension", "result"))
_M_SHED = _REG.counter(
    "serving_usage_shed_total",
    "requests shed at admission, attributed per tenant",
    ("tenant",))
_M_ADAPTER_TOKENS = _REG.counter(
    "serving_usage_adapter_tokens_total",
    "decode tokens attributed per tenant and LoRA adapter (series "
    "exist only for requests that named an adapter; cardinality is "
    "bounded by the adapter registry)", ("tenant", "adapter"))
_M_TENANTS = _REG.gauge(
    "serving_usage_tenants",
    "distinct tenants currently tracked (LRU-bounded by "
    "FLAGS_serving_usage_max_tenants)")
_M_EVICTED = _REG.counter(
    "serving_usage_evicted_tenants_total",
    "tenants folded into the (evicted) rollup at the LRU cardinality "
    "cap")

# the reserved rollup label evicted tenants fold into — never evicted
# itself, so the registry's tenant cardinality is capped at the table
# capacity + 1 at every instant
EVICTED_TENANT = "(evicted)"

# metric families carrying a tenant label; eviction folds their series
_TENANT_METRICS = (_M_TOKENS, _M_REQS, _M_PAGE_SECONDS, _M_QUEUE_SECONDS,
                   _M_SPILL_BYTES, _M_PREEMPT, _M_SLO, _M_SHED,
                   _M_ADAPTER_TOKENS)

_AGG_INT_FIELDS = (
    "requests", "finished", "goodput_requests",
    "prefill_computed_tokens", "prefill_cached_tokens", "decode_tokens",
    "prefill_chunks", "spec_proposed_tokens", "spec_accepted_tokens",
    "pages_allocated", "spilled_pages", "spill_bytes",
    "restored_pages", "restore_bytes", "preemptions", "replays", "shed")
_AGG_FLOAT_FIELDS = ("queue_seconds", "page_seconds", "host_page_seconds")

_GOODPUT_REASONS = ("length", "eos")


def _zero_row() -> dict:
    row = {f: 0 for f in _AGG_INT_FIELDS}
    for f in _AGG_FLOAT_FIELDS:
        row[f] = 0.0
    row["slo"] = {}
    row["adapters"] = {}
    return row


def _merge_row(dst: dict, src: dict):
    """Raw-merge one tenant row into another: numeric fields sum,
    nested dicts (the slo verdict table) recurse — never averages, the
    same discipline the router applies to latency buckets."""
    for k, v in src.items():
        if isinstance(v, dict):
            _merge_row(dst.setdefault(k, {}), v)
        elif isinstance(v, (int, float)):
            dst[k] = dst.get(k, 0) + v
        else:
            dst.setdefault(k, v)


def request_ledger(req) -> dict:
    """The per-request cost ledger as a plain dict — every field reads
    off the Request, so this works with or without a live meter
    (page-seconds stay 0.0 until the meter folds them in)."""
    return {
        "tenant": getattr(req, "tenant", "anon"),
        "queue_seconds": req.queue_seconds,
        "prefill_computed_tokens": req.prefill_computed_tokens,
        "prefill_cached_tokens": req.prefill_cached_tokens,
        "prefill_chunks": req.prefill_chunks,
        "decode_tokens": req.num_generated,
        "spec_proposed_tokens": req.spec_proposed_tokens,
        "spec_accepted_tokens": req.spec_accepted_tokens,
        "pages_allocated": req.pages_allocated,
        "page_seconds": req.page_seconds,
        "host_page_seconds": req.host_page_seconds,
        "spilled_pages": req.spilled_pages,
        "spill_bytes": req.spill_bytes,
        "restored_pages": req.restored_pages,
        "restore_bytes": req.restore_bytes,
        "preemptions": req.preemptions,
        "replays": req.replays,
        "adapter": getattr(req, "adapter", None),
    }


class TenantTable:
    """LRU-bounded tenant aggregate table.

    ``resolve`` admits (or touches) a tenant and returns its aggregate
    row; admission past ``capacity`` evicts the least-recently-used
    tenant, folding its row into :attr:`overflow` (surfaced as the
    ``"(evicted)"`` tenant) and invoking :attr:`on_evict` so the meter
    can fold the matching metric series — bounded label cardinality at
    every instant, with totals conserved across eviction."""

    def __init__(self, capacity: int = 64):
        self.capacity = max(1, int(capacity))
        self._aggs: OrderedDict[str, dict] = OrderedDict()
        self.overflow = _zero_row()
        self.evicted_tenants = 0
        self.on_evict = None          # callable(name) — meter hook

    def __len__(self) -> int:
        return len(self._aggs)

    def __contains__(self, name) -> bool:
        return str(name) in self._aggs

    def items(self):
        return list(self._aggs.items())

    @staticmethod
    def canonical(tenant) -> str:
        name = str(tenant).strip() if tenant is not None else ""
        return name or "anon"

    def resolve(self, tenant) -> tuple[str, dict]:
        """Canonical ``(name, row)`` for ``tenant``, admitting it
        (evicting LRU at capacity) and marking it most-recent."""
        name = self.canonical(tenant)
        row = self._aggs.get(name)
        if row is not None:
            self._aggs.move_to_end(name)
            return name, row
        while len(self._aggs) >= self.capacity:
            victim, vrow = self._aggs.popitem(last=False)
            _merge_row(self.overflow, vrow)
            self.evicted_tenants += 1
            _M_EVICTED.inc()
            if self.on_evict is not None:
                self.on_evict(victim)
        row = _zero_row()
        self._aggs[name] = row
        _M_TENANTS.set(len(self._aggs))
        return name, row

    def charge_row(self, tenant) -> dict:
        """Aggregate row for charging *without* LRU promotion or
        admission; unknown (evicted) tenants charge the overflow
        rollup — late charges never resurrect an evicted label."""
        return self._aggs.get(str(tenant), self.overflow)


class UsageMeter:
    """Per-request / per-tenant cost meter for one serving engine.

    The engine binds its clock at construction time (``clock=None``
    inherits the engine's — fake clocks in tests, ``time.monotonic``
    in production) and calls the ``on_*`` hooks at the existing
    seams; the BlockManager feeds ``on_hold`` / ``on_release`` for the
    page-seconds integral.  Every hook ticks the integrator before
    mutating holder state, so residency is exact on the shared clock.
    """

    def __init__(self, *, max_tenants: int = 64, clock=None):
        self._clock = clock
        self._lock = make_lock("UsageMeter._lock")
        self.tenants = TenantTable(max_tenants)
        self.tenants.on_evict = self._fold_evicted_tenant
        # live requests: seq id -> (tenant, Request)
        self._live: dict[int, tuple] = {}
        # device tier: page -> holder seqs; seq -> charge rate
        # (sum of 1/|holders| over held pages) and unfolded accumulator
        self._holders: dict[int, list] = {}
        self._rate: dict[int, float] = {}
        self._acc: dict[int, float] = {}
        self._pool_acc = 0.0              # integral of live-pages dt
        # host spill tier: digest -> charged tenant / parking seq
        self._host_tenant: dict[str, str] = {}
        self._host_count: dict[str, int] = {}     # tenant -> digests
        self._host_parker: dict[str, int] = {}
        self._parked_by: dict[int, set] = {}      # seq -> digests
        self._host_req_acc: dict[int, float] = {}
        self._host_pool_acc = 0.0         # integral of parked-pages dt
        self._last: float | None = None

    # ------------------------------------------------------------ clock
    def now(self) -> float:
        return (self._clock or time.monotonic)()

    def _tick(self, now: float | None = None):
        """Advance both residency integrals to ``now`` (callers hold
        the lock).  Rates only change at hook boundaries, so piecewise-
        constant integration is exact."""
        now = self.now() if now is None else float(now)
        last = self._last
        if last is not None and now > last:
            dt = now - last
            if self._rate:
                acc = self._acc
                for s, r in self._rate.items():
                    acc[s] = acc.get(s, 0.0) + r * dt
            self._pool_acc += len(self._holders) * dt
            if self._host_count:
                for tenant, n in self._host_count.items():
                    amt = n * dt
                    self.tenants.charge_row(tenant)[
                        "host_page_seconds"] += amt
                    _M_PAGE_SECONDS.labels(tenant, "host").inc(amt)
                self._host_pool_acc += len(self._host_tenant) * dt
            if self._parked_by:
                for s, digests in self._parked_by.items():
                    self._host_req_acc[s] = (
                        self._host_req_acc.get(s, 0.0)
                        + len(digests) * dt)
        if last is None or now > last:
            self._last = now

    # -------------------------------------------------- request lifecycle
    def on_submit(self, req):
        """Admit the request's tenant and start attributing to it."""
        with self._lock:
            self._tick()
            tenant, row = self.tenants.resolve(
                getattr(req, "tenant", None))
            req.tenant = tenant          # canonicalized ("" -> "anon")
            self._live[req.id] = (tenant, req)
            row["requests"] += 1

    def on_finish(self, req, reason: str, now: float | None = None):
        """Fold the request's scalar ledger into its tenant aggregate.
        Page-seconds fold when the last page releases (the scheduler
        evicts — and frees pages — *after* the engine finalizes)."""
        with self._lock:
            self._tick(now)
            entry = self._live.get(req.id)
            if entry is None:
                return
            tenant, _ = entry
            row = self.tenants.charge_row(tenant)
            row["finished"] += 1
            if reason in _GOODPUT_REASONS:
                row["goodput_requests"] += 1
            row["prefill_computed_tokens"] += req.prefill_computed_tokens
            row["prefill_cached_tokens"] += req.prefill_cached_tokens
            row["decode_tokens"] += req.num_generated
            row["prefill_chunks"] += req.prefill_chunks
            row["spec_proposed_tokens"] += req.spec_proposed_tokens
            row["spec_accepted_tokens"] += req.spec_accepted_tokens
            row["queue_seconds"] += req.queue_seconds
            row["pages_allocated"] += req.pages_allocated
            row["spilled_pages"] += req.spilled_pages
            row["spill_bytes"] += req.spill_bytes
            row["restored_pages"] += req.restored_pages
            row["restore_bytes"] += req.restore_bytes
            row["preemptions"] += req.preemptions
            row["replays"] += req.replays
            _M_REQS.labels(tenant, str(reason)).inc()
            _M_TOKENS.labels(tenant, "prefill_computed").inc(
                req.prefill_computed_tokens)
            _M_TOKENS.labels(tenant, "prefill_cached").inc(
                req.prefill_cached_tokens)
            _M_TOKENS.labels(tenant, "decode").inc(req.num_generated)
            _M_QUEUE_SECONDS.labels(tenant).inc(req.queue_seconds)
            adapter = getattr(req, "adapter", None)
            if adapter:
                cell = row["adapters"].setdefault(
                    str(adapter), {"requests": 0, "decode_tokens": 0})
                cell["requests"] += 1
                cell["decode_tokens"] += req.num_generated
                _M_ADAPTER_TOKENS.labels(tenant, str(adapter)).inc(
                    req.num_generated)
            if req.spill_bytes:
                _M_SPILL_BYTES.labels(tenant).inc(req.spill_bytes)
            if req.preemptions:
                _M_PREEMPT.labels(tenant).inc(req.preemptions)
            # stop per-request host charging (the tenant keeps paying
            # for its parked digests until the host tier evicts them)
            self._release_host(req.id, req)
            if req.id not in self._rate:
                self._fold_pages(req.id, tenant, req)

    # ----------------------------------------------- device page-seconds
    def on_hold(self, seq: int, pages, fresh: int = 0):
        """``seq`` took references on ``pages`` (BlockManager admission
        hook); ``fresh`` of them were newly acquired from the pool."""
        with self._lock:
            self._tick()
            rate = self._rate.get(seq, 0.0)
            for p in pages:
                holders = self._holders.get(p)
                if holders is None:
                    self._holders[p] = [seq]
                    rate += 1.0
                else:
                    k = len(holders)
                    # existing holders' share drops 1/k -> 1/(k+1)
                    adj = 1.0 / (k + 1) - 1.0 / k
                    for h in holders:
                        self._rate[h] += adj
                    holders.append(seq)
                    rate += 1.0 / (k + 1)
            self._rate[seq] = rate
            self._acc.setdefault(seq, 0.0)
            if fresh:
                entry = self._live.get(seq)
                if entry is not None:
                    entry[1].pages_allocated += int(fresh)

    def on_release(self, seq: int, pages):
        """``seq`` dropped all its page references (free_seq)."""
        with self._lock:
            self._tick()
            for p in pages:
                holders = self._holders.get(p)
                if not holders or seq not in holders:
                    continue
                holders.remove(seq)
                k = len(holders)
                if k == 0:
                    del self._holders[p]
                else:
                    adj = 1.0 / k - 1.0 / (k + 1)
                    for h in holders:
                        self._rate[h] += adj
            self._rate.pop(seq, None)
            acc = self._acc.pop(seq, 0.0)
            entry = self._live.get(seq)
            if entry is None:
                # a sequence the engine never registered (unit tests
                # driving the BlockManager directly): conserve the
                # charge under the default tenant — resolve, not
                # charge_row, so the table row matches the metric
                # series instead of landing in the eviction rollup
                _, row = self.tenants.resolve("anon")
                row["page_seconds"] += acc
                _M_PAGE_SECONDS.labels("anon", "device").inc(acc)
                return
            tenant, req = entry
            req.page_seconds += acc
            if req.is_finished():
                self._fold_pages(seq, tenant, req)

    def _fold_pages(self, seq: int, tenant: str, req):
        """Terminal fold: the request is finished and holds no pages —
        move its total page-seconds into the tenant row exactly once
        (dropping it from the live map makes a second fold impossible)."""
        if self._live.pop(seq, None) is None:
            return
        row = self.tenants.charge_row(tenant)
        row["page_seconds"] += req.page_seconds
        _M_PAGE_SECONDS.labels(tenant, "device").inc(req.page_seconds)

    # ------------------------------------------------- host (spill) tier
    def on_host_park(self, req, digest: str):
        """One spilled page parked under ``digest`` for ``req``."""
        with self._lock:
            self._tick()
            if digest in self._host_tenant:
                return
            entry = self._live.get(req.id)
            tenant = entry[0] if entry is not None \
                else self.tenants.canonical(getattr(req, "tenant", None))
            self._host_tenant[digest] = tenant
            self._host_count[tenant] = \
                self._host_count.get(tenant, 0) + 1
            self._host_parker[digest] = req.id
            self._parked_by.setdefault(req.id, set()).add(digest)

    def on_host_evict(self, digest: str):
        """The host tier dropped ``digest`` (LRU bound or discard)."""
        with self._lock:
            self._tick()
            tenant = self._host_tenant.pop(digest, None)
            if tenant is None:
                return
            n = self._host_count.get(tenant, 0) - 1
            if n > 0:
                self._host_count[tenant] = n
            else:
                self._host_count.pop(tenant, None)
            parker = self._host_parker.pop(digest, None)
            if parker is not None:
                held = self._parked_by.get(parker)
                if held is not None:
                    held.discard(digest)
                    if not held:
                        del self._parked_by[parker]

    def on_host_release(self, req):
        """``req`` resumed (or finished): stop charging its ledger for
        parked digests; the tenant track keeps accruing until the host
        tier evicts the copies."""
        with self._lock:
            self._tick()
            self._release_host(req.id, req)

    def _release_host(self, seq: int, req):
        req.host_page_seconds += self._host_req_acc.pop(seq, 0.0)
        for digest in self._parked_by.pop(seq, ()):
            self._host_parker.pop(digest, None)

    # ---------------------------------------------------- SLO / shedding
    def slo_verdict(self, req, dim: str, ok: bool):
        """``SLOTracker.verdict_hook`` adapter: mirror each per-request
        SLO verdict onto the request's tenant."""
        with self._lock:
            entry = self._live.get(req.id)
            tenant = entry[0] if entry is not None \
                else self.tenants.canonical(getattr(req, "tenant", None))
            row = self.tenants.charge_row(tenant)
            result = "good" if ok else "violation"
            cell = row["slo"].setdefault(str(dim),
                                         {"good": 0, "violation": 0})
            cell[result] += 1
            _M_SLO.labels(tenant, str(dim), result).inc()

    def on_shed(self, tenant):
        with self._lock:
            name, row = self.tenants.resolve(tenant)
            row["shed"] += 1
            _M_SHED.labels(name).inc()

    def heaviest_tenant(self) -> str | None:
        """The tenant with the largest page-second bill (device + host,
        live accrual included) — the fair-share shed/preempt target.
        Excludes the ``"(evicted)"`` rollup; deterministic tie-break."""
        with self._lock:
            self._tick()
            totals: dict[str, float] = {}
            for name, row in self.tenants.items():
                totals[name] = (row["page_seconds"]
                                + row["host_page_seconds"])
            for seq, (tenant, req) in self._live.items():
                totals[tenant] = (totals.get(tenant, 0.0)
                                  + req.page_seconds
                                  + self._acc.get(seq, 0.0))
            if not totals:
                return None
            return max(totals.items(), key=lambda kv: (kv[1], kv[0]))[0]

    # ---------------------------------------------- eviction / snapshot
    def _fold_evicted_tenant(self, name: str):
        """TenantTable eviction hook: fold the tenant's metric series
        into the rollup label and re-key any parked host digests so
        later ticks charge the rollup instead of resurrecting the
        evicted label."""
        for fam in _TENANT_METRICS:
            fam.fold_label("tenant", name, EVICTED_TENANT)
        # the hook only ever fires from TenantTable calls made by meter
        # methods that already hold self._lock (a plain Lock — taking
        # it again here would deadlock), so these writes are protected
        moved = 0
        for digest, tenant in list(self._host_tenant.items()):
            if tenant == name:
                # tpu-lint: disable=lock-unlocked-write
                self._host_tenant[digest] = EVICTED_TENANT
                moved += 1
        if moved:
            self._host_count.pop(name, None)
            # tpu-lint: disable=lock-unlocked-write
            self._host_count[EVICTED_TENANT] = \
                self._host_count.get(EVICTED_TENANT, 0) + moved
        # live requests of the evicted tenant keep charging it by name;
        # their terminal fold lands in the overflow row (charge_row)

    def conservation(self) -> dict:
        """The conservation identities, as charged-vs-pool deltas.
        Both are exactly zero up to float associativity; tests and the
        perf_gate pin ``round(delta, 6) == 0``."""
        with self._lock:
            self._tick()
            return self._conservation_locked()

    def _conservation_locked(self) -> dict:
        charged = self.tenants.overflow["page_seconds"]
        for _name, row in self.tenants.items():
            charged += row["page_seconds"]
        for seq, (_tenant, req) in self._live.items():
            charged += req.page_seconds
        # unfolded accumulators (live holders + unregistered seqs)
        charged += sum(self._acc.values())
        # requests that finished+released already folded; requests that
        # released but were never registered folded into "anon"
        host = self.tenants.overflow["host_page_seconds"]
        for _name, row in self.tenants.items():
            host += row["host_page_seconds"]
        return {
            "device_page_seconds": self._pool_acc,
            "device_delta": round(self._pool_acc - charged, 6),
            "host_page_seconds": self._host_pool_acc,
            "host_delta": round(self._host_pool_acc - host, 6),
            "live_pages": len(self._holders),
            "host_parked": len(self._host_tenant),
        }

    def snapshot(self) -> dict:
        """The per-tenant usage table (live page-second accrual folded
        in), mergeable across replicas with :func:`merge_usage`."""
        with self._lock:
            self._tick()
            tenants: dict[str, dict] = {}
            for name, row in self.tenants.items():
                copy = {k: (dict(v) if isinstance(v, dict) else v)
                        for k, v in row.items()}
                copy["slo"] = {d: dict(c)
                               for d, c in row["slo"].items()}
                copy["adapters"] = {a: dict(c)
                                    for a, c in row["adapters"].items()}
                tenants[name] = copy
            for seq, (tenant, req) in self._live.items():
                dst = tenants.setdefault(tenant, _zero_row())
                dst["page_seconds"] += (req.page_seconds
                                        + self._acc.get(seq, 0.0))
            if any(v for k, v in self.tenants.overflow.items()
                   if k != "slo") or self.tenants.overflow["slo"]:
                _merge_row(tenants.setdefault(EVICTED_TENANT,
                                              _zero_row()),
                           self.tenants.overflow)
            return {
                "tenants": tenants,
                "evicted_tenants": self.tenants.evicted_tenants,
                "live_requests": len(self._live),
                "conservation": self._conservation_locked(),
            }


def merge_usage(snapshots) -> dict:
    """Raw-merge per-replica usage snapshots: per-tenant counters sum
    (recursing into the slo table), never averaging derived values —
    the same discipline as the fleet latency-bucket merge.  ``None``
    entries (dead replicas, metering off) are skipped."""
    tenants: dict[str, dict] = {}
    evicted = 0
    live = 0
    merged = 0
    for snap in snapshots:
        if not snap:
            continue
        merged += 1
        for name, row in (snap.get("tenants") or {}).items():
            _merge_row(tenants.setdefault(name, {}), row)
        evicted += int(snap.get("evicted_tenants") or 0)
        live += int(snap.get("live_requests") or 0)
    return {"tenants": tenants, "evicted_tenants": evicted,
            "live_requests": live, "replicas": merged}


# --------------------------------------------------- active-meter global
_active: UsageMeter | None = None


def active_usage() -> UsageMeter | None:
    """The process's live usage meter (None = metering off)."""
    return _active


def set_active_usage(meter: UsageMeter | None):
    global _active
    _active = meter
