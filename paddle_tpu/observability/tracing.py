"""Dapper-style request tracing + engine flight recorder.

Two bounded, always-on event streams that answer the questions the
metrics registry cannot:

  * :class:`Tracer` — per-request spans with W3C ``traceparent``
    propagation.  "Where did THIS request spend its 900 ms" across
    client -> router -> replica -> engine: every layer starts spans
    under one 128-bit trace id, carried over HTTP in the standard
    ``traceparent: 00-<trace>-<span>-01`` header.  Finished spans land
    in a bounded per-process ring and export as chrome://tracing JSON
    on the same ``perf_counter`` clock the native host tracer
    (csrc/trace.cc) and the registry's sampled counter events use —
    ``profiler.export_host_trace`` merges all three onto one timeline.
  * :class:`FlightRecorder` — a fixed-size ring of recent
    scheduler/engine/BlockManager events (admit / evict / page-alloc /
    CoW / backpressure / host-sync).  When the serving watchdog
    detects a stalled decode loop it dumps this ring: the postmortem
    of what the engine was doing when it wedged (reference analog:
    CommTaskManager's hang dumps).

Both are pure stdlib, lock-bounded, and cheap enough to stay on in
production: recording a span is two ``perf_counter`` calls and one
deque append.
"""
from __future__ import annotations

import contextvars
import itertools
import json
import os
import threading
import time
from collections import deque
from typing import NamedTuple

from ..sanitizer import make_lock

__all__ = ["Span", "SpanContext", "Tracer", "FlightRecorder",
           "tracer", "flight_recorder", "format_traceparent",
           "parse_traceparent", "TRACEPARENT_HEADER"]

TRACEPARENT_HEADER = "traceparent"

_CURRENT: contextvars.ContextVar = contextvars.ContextVar(
    "paddle_tpu_current_span", default=None)

# sentinel: "no parent passed — inherit the context-local span"
_INHERIT = object()


class SpanContext(NamedTuple):
    """The portable identity of a span: what crosses process/thread
    boundaries (and the wire, as a ``traceparent`` header)."""
    trace_id: str       # 32 lowercase hex chars
    span_id: str        # 16 lowercase hex chars


def format_traceparent(ctx: SpanContext) -> str:
    """W3C Trace Context header value (version 00, sampled)."""
    return f"00-{ctx.trace_id}-{ctx.span_id}-01"


def parse_traceparent(header) -> SpanContext | None:
    """Parse a ``traceparent`` header; returns None on anything
    malformed (tracing must never fail a request)."""
    if not header or not isinstance(header, str):
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(version, 16), int(trace_id, 16), int(span_id, 16)
    except ValueError:
        return None
    if version == "ff" or trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return SpanContext(trace_id.lower(), span_id.lower())


def _new_trace_id() -> str:
    return os.urandom(16).hex()


def _new_span_id() -> str:
    return os.urandom(8).hex()


class Span:
    """One named interval on the trace timeline.

    Created via :meth:`Tracer.start_span`; finish with :meth:`end` (or
    use as a context manager, which also makes it the context-local
    parent for spans started inside).  Timestamps are
    ``time.perf_counter()`` so spans line up with the native host
    tracer and sampled counter tracks.
    """

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "start",
                 "end_time", "attributes", "events", "pid", "tid",
                 "thread_name", "_tracer", "_token", "_ended")

    def __init__(self, tracer: "Tracer", name: str, trace_id: str,
                 parent_id: str | None, attributes: dict | None):
        self._tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = _new_span_id()
        self.parent_id = parent_id
        self.start = time.perf_counter()
        self.end_time: float | None = None
        self.attributes = dict(attributes) if attributes else {}
        self.events: list[dict] = []
        self.pid = os.getpid()
        t = threading.current_thread()
        self.tid = t.native_id if t.native_id is not None else t.ident
        self.thread_name = t.name
        self._token = None
        self._ended = False

    @property
    def context(self) -> SpanContext:
        return SpanContext(self.trace_id, self.span_id)

    @property
    def duration(self) -> float | None:
        return None if self.end_time is None else self.end_time - self.start

    def set_attribute(self, key: str, value) -> "Span":
        self.attributes[key] = value
        return self

    def add_event(self, name: str, **attrs):
        """Point-in-time annotation inside the span (eviction, retry,
        park...) — exported as a chrome 'i' (instant) event."""
        self.events.append({"ts": time.perf_counter(), "name": name,
                            "attrs": attrs})

    def end(self, end_time: float | None = None):
        """Close the span and commit it to the tracer ring.  Idempotent
        — a double end() (finalize paths racing) records once."""
        if self._ended:
            return
        self._ended = True
        self.end_time = time.perf_counter() if end_time is None else end_time
        self._tracer._commit(self)

    def __enter__(self) -> "Span":
        self._token = _CURRENT.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _CURRENT.reset(self._token)
            self._token = None
        if exc is not None:
            self.attributes.setdefault("error", repr(exc))
        self.end()

    def to_dict(self) -> dict:
        return {"name": self.name, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "start": self.start, "end": self.end_time,
                "duration_s": self.duration, "pid": self.pid,
                "tid": self.tid, "thread": self.thread_name,
                "attributes": dict(self.attributes),
                "events": [dict(e) for e in self.events]}

    def __repr__(self):
        return (f"Span({self.name!r}, trace={self.trace_id[:8]}…, "
                f"dur={self.duration})")


class Tracer:
    """Span factory + bounded ring of finished spans.

    ``start_span`` with no explicit ``parent`` inherits the
    context-local span (set by using a span as a context manager) —
    that is how ``client.completion`` nests under ``router.request``
    without either layer knowing the other's internals.  Cross-thread
    parenting (HTTP handler -> engine worker) passes an explicit
    :class:`SpanContext` instead.
    """

    def __init__(self, max_spans: int | None = None):
        if max_spans is None:
            try:
                from ..flags import FLAGS
                max_spans = int(FLAGS.get("FLAGS_trace_buffer_size")
                                or 4096)
            except Exception:   # standalone use
                max_spans = 4096
        self.max_spans = int(max_spans)
        self._spans: deque[Span] = deque(maxlen=self.max_spans)
        self._lock = make_lock("Tracer._lock")
        self.spans_dropped = 0
        self.spans_recorded = 0

    # ------------------------------------------------------------- spans
    def start_span(self, name: str, parent=_INHERIT,
                   attributes: dict | None = None) -> Span:
        """Open a span.  ``parent`` may be a :class:`Span`, a
        :class:`SpanContext`, ``None`` (force a new root trace), or
        omitted (inherit the context-local current span)."""
        if parent is _INHERIT:
            parent = _CURRENT.get()
        if isinstance(parent, Span):
            parent = parent.context
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_id = _new_trace_id(), None
        return Span(self, name, trace_id, parent_id, attributes)

    def record_span(self, name: str, start: float, end: float, *,
                    parent=None, attributes: dict | None = None) -> Span:
        """Record an already-measured interval (RecordEvent capture,
        sampling sections) without the context-manager machinery."""
        start_f, end_f = float(start), float(end)    # before the span
        span = self.start_span(name, parent=parent, attributes=attributes)
        span.start = start_f
        span.end(end_f)
        return span

    def current_span(self) -> Span | None:
        return _CURRENT.get()

    def _commit(self, span: Span):
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.spans_dropped += 1
            self._spans.append(span)
            self.spans_recorded += 1

    # ----------------------------------------------------------- queries
    def spans(self, *, name: str | None = None,
              trace_id: str | None = None) -> list[Span]:
        """Snapshot of the finished-span ring, optionally filtered."""
        with self._lock:
            out = list(self._spans)
        if name is not None:
            out = [s for s in out if s.name == name]
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def reset(self):
        with self._lock:
            self._spans.clear()
            self.spans_dropped = 0
            self.spans_recorded = 0

    def __len__(self):
        with self._lock:
            return len(self._spans)

    # ------------------------------------------------------------ export
    def chrome_events(self, pid: int | None = None) -> list[dict]:
        """Finished spans as chrome-trace events: one "X" (complete)
        event per span on its real thread row, an "i" (instant) event
        per span event, plus "M" thread-name metadata so every
        EngineWorker / HTTP handler thread renders as its own named
        row instead of collapsing onto tid 0."""
        spans = self.spans()
        out: list[dict] = []
        threads_seen: dict[tuple, str] = {}
        for s in spans:
            p = pid if pid is not None else s.pid
            threads_seen.setdefault((p, s.tid), s.thread_name)
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            args.update({k: v for k, v in s.attributes.items()})
            out.append({"name": s.name, "ph": "X", "pid": p,
                        "tid": s.tid, "ts": s.start * 1e6,
                        "dur": ((s.end_time or s.start) - s.start) * 1e6,
                        "cat": "tracing", "args": args})
            for ev in s.events:
                out.append({"name": f"{s.name}.{ev['name']}", "ph": "i",
                            "pid": p, "tid": s.tid,
                            "ts": ev["ts"] * 1e6, "s": "t",
                            "cat": "tracing",
                            "args": dict(ev["attrs"],
                                         trace_id=s.trace_id)})
        for (p, tid), tname in threads_seen.items():
            out.append({"name": "thread_name", "ph": "M", "pid": p,
                        "tid": tid, "args": {"name": tname}})
        return out

    def to_dict(self) -> dict:
        return {"spans": [s.to_dict() for s in self.spans()],
                "recorded": self.spans_recorded,
                "dropped": self.spans_dropped}


class FlightRecorder:
    """Fixed-size ring of recent engine events — the crash recorder.

    Every record is a dict with a monotonically increasing ``seq``, a
    ``perf_counter`` timestamp, a ``category`` (scheduler / engine /
    block_manager / server / watchdog), an ``event`` name, and
    free-form attributes.  ``snapshot()`` is what ``/debug/flight``
    serves and what the watchdog dumps on a stall.
    """

    def __init__(self, capacity: int | None = None):
        if capacity is None:
            try:
                from ..flags import FLAGS
                capacity = int(FLAGS.get("FLAGS_flight_recorder_size")
                               or 512)
            except Exception:
                capacity = 512
        self.capacity = int(capacity)
        self._ring: deque[dict] = deque(maxlen=self.capacity)
        self._lock = make_lock("FlightRecorder._lock")
        self._seq = itertools.count()

    def record(self, category: str, event: str, **attrs):
        entry = {"seq": next(self._seq), "ts": time.perf_counter(),
                 "category": category, "event": event}
        if attrs:
            entry.update(attrs)
        with self._lock:
            self._ring.append(entry)

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    def clear(self):
        with self._lock:
            self._ring.clear()

    def __len__(self):
        with self._lock:
            return len(self._ring)

    def dump(self, path: str) -> str:
        with open(path, "w") as f:
            json.dump({"capacity": self.capacity,
                       "events": self.snapshot()}, f, indent=2)
        return path


_tracer = Tracer()
_flight = FlightRecorder()


def tracer() -> Tracer:
    return _tracer


def flight_recorder() -> FlightRecorder:
    return _flight
