"""Continuous sampling profiler, phase-attributed (reference analog:
the host profiler + timer statistic tables of PAPER.md §2.14, grown
into an always-on "why is it slow" layer).

:class:`SamplingProfiler` walks ``sys._current_frames()`` on an
interval and aggregates per-thread stacks into a bounded table keyed by
``(phase, thread, stack)``.  *Phase* comes from a caller-supplied
``phases`` callable mapping thread idents to what that thread is doing
right now — the serving server wires it to the engine's
``current_phase`` attribute (published at the same seams that charge
``serving_step_phase_seconds_total``), so a hot stack splits into
prefill / prefill_chunk / decode / verify / host_sync / idle buckets
instead of one undifferentiated engine blob.

Outputs:

  * ``folded()`` — Brendan-Gregg folded stacks
    (``phase;thread;frame;... count``), flamegraph-ready;
  * ``chrome_events()`` — instant events on the same ``perf_counter``
    microsecond scale as :meth:`Tracer.chrome_events`, so samples merge
    into the existing chrome trace export;
  * ``snapshot()`` — the bounded JSON bundle DiagnosticCapture embeds.

The shape follows the watchdog/timeseries split exactly: ``sample(now)``
is one explicit step driven by a fake clock in unit tests (sub-second
suites); ``start_sampling()`` runs it on a daemon thread in production
and is a no-op for a non-positive interval.  With
``FLAGS_obs_profile_interval_s`` unset no profiler object is ever
constructed — the serving path's only cost is an attribute test, the
same zero-overhead contract as fault injection and the sanitizer
(pinned by the perf_gate ``profiling`` scenario).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from collections import deque

from ..sanitizer import make_lock
from .registry import default_registry

__all__ = ["SamplingProfiler", "active_profiler", "set_active_profiler"]

_M_SAMPLES = default_registry().counter(
    "obs_profile_samples_total",
    "sampling-profiler sweeps over sys._current_frames")
_M_DROPPED = default_registry().counter(
    "obs_profile_dropped_total",
    "per-thread stack observations dropped at the distinct-stack cap")


class SamplingProfiler:
    """Aggregating stack sampler over every live thread.

    ``phases`` (optional) is a zero-argument callable returning
    ``{thread_ident: phase_str}``; threads it does not name are
    attributed to phase ``"other"``.  ``max_stacks`` bounds the number
    of distinct ``(phase, thread, stack)`` keys kept (further distinct
    stacks count into ``dropped`` — fixed memory, like every other ring
    in observability/).  The sweeping thread never samples itself.
    """

    MAX_SECONDS = 60.0      # cap for on-demand /debug/profile windows

    def __init__(self, interval_s: float = 0.01, *,
                 phases=None, max_stacks: int = 2048,
                 max_depth: int = 64, ring_size: int = 4096,
                 clock=time.perf_counter):
        self.interval_s = float(interval_s)
        self._phases = phases
        self.max_stacks = int(max_stacks)
        self.max_depth = int(max_depth)
        self._clock = clock
        self._lock = make_lock("SamplingProfiler._lock")
        # (phase, thread_name, stack_tuple) -> observation count
        self._stacks: dict[tuple, int] = {}
        # bounded recent-sample ring for the chrome-trace merge:
        # (t, ident, thread_name, phase, leaf_frame)
        self._ring: deque = deque(maxlen=int(ring_size))
        self.samples = 0            # sweeps taken (python mirror)
        self.observations = 0       # per-thread stacks recorded
        self.dropped = 0            # observations lost to max_stacks
        self.started_at: float | None = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---------------------------------------------------------- sampling
    def sample(self, now: float | None = None) -> int:
        """One sweep: walk every thread's current frame, attribute it
        to a phase, and bump the aggregate table.  Returns the number
        of stacks observed.  Explicit ``now`` keeps tests on a fake
        clock; production passes nothing."""
        now = self._clock() if now is None else float(now)
        try:
            phase_of = self._phases() if self._phases is not None else {}
        except Exception:
            phase_of = {}           # a broken source must not kill sweeps
        frames = sys._current_frames()
        names = {t.ident: t.name for t in threading.enumerate()}
        me = threading.get_ident()
        seen = 0
        with self._lock:
            if self.started_at is None:
                self.started_at = now
            self.samples += 1
            for ident, frame in frames.items():
                if ident == me:
                    continue        # never profile the sampler itself
                stack = self._walk(frame)
                if not stack:
                    continue
                phase = str(phase_of.get(ident, "other"))
                key = (phase, names.get(ident, f"thread-{ident}"),
                       stack)
                n = self._stacks.get(key)
                if n is None and len(self._stacks) >= self.max_stacks:
                    self.dropped += 1
                    continue
                self._stacks[key] = (n or 0) + 1
                self.observations += 1
                seen += 1
                self._ring.append((now, ident, key[1], phase,
                                   stack[-1]))
        _M_SAMPLES.inc()
        return seen

    def _walk(self, frame) -> tuple:
        """Root-first tuple of ``file:function`` frames (function
        granularity, not line — line-level keys explode the distinct-
        stack table without helping a flamegraph)."""
        out = []
        while frame is not None and len(out) < self.max_depth:
            code = frame.f_code
            out.append(f"{os.path.basename(code.co_filename)}"
                       f":{code.co_name}")
            frame = frame.f_back
        out.reverse()
        return tuple(out)

    # ----------------------------------------------------------- outputs
    def folded(self, top: int | None = None) -> str:
        """Folded-stack text: ``phase;thread;frame;... count`` per
        line, heaviest first — feed to flamegraph.pl / speedscope."""
        with self._lock:
            items = sorted(self._stacks.items(),
                           key=lambda kv: -kv[1])
        if top is not None:
            items = items[:int(top)]
        lines = []
        for (phase, thread, stack), count in items:
            lines.append(";".join((phase, thread) + stack)
                         + f" {count}")
        return "\n".join(lines)

    def top_stacks(self, n: int = 50) -> list[dict]:
        with self._lock:
            items = sorted(self._stacks.items(),
                           key=lambda kv: -kv[1])[:int(n)]
        return [{"phase": phase, "thread": thread,
                 "stack": list(stack), "count": count}
                for (phase, thread, stack), count in items]

    def by_phase(self) -> dict[str, int]:
        """phase -> observation count (the attribution histogram)."""
        out: dict[str, int] = {}
        with self._lock:
            for (phase, _, _), count in self._stacks.items():
                out[phase] = out.get(phase, 0) + count
        return dict(sorted(out.items(), key=lambda kv: -kv[1]))

    def chrome_events(self, pid: int | None = None) -> list[dict]:
        """Recent samples as chrome-trace instant events, on the same
        perf_counter microsecond timebase as Tracer.chrome_events —
        concatenating the two lists yields one merged timeline."""
        pid = os.getpid() if pid is None else pid
        with self._lock:
            ring = list(self._ring)
        return [{"name": f"sample:{phase}", "ph": "i", "s": "t",
                 "ts": t * 1e6, "pid": pid, "tid": ident,
                 "cat": "profile",
                 "args": {"phase": phase, "leaf": leaf,
                          "thread": name}}
                for t, ident, name, phase, leaf in ring]

    def stats(self) -> dict:
        with self._lock:
            return {"interval_s": self.interval_s,
                    "samples": self.samples,
                    "observations": self.observations,
                    "distinct_stacks": len(self._stacks),
                    "dropped": self.dropped,
                    "started_at": self.started_at}

    def snapshot(self, top: int = 50) -> dict:
        """Bounded JSON bundle: what DiagnosticCapture embeds and
        ``observability.dump()`` writes as ``profile.json``."""
        return {"stats": self.stats(), "by_phase": self.by_phase(),
                "top_stacks": self.top_stacks(top)}

    def reset(self):
        with self._lock:
            self._stacks.clear()
            self._ring.clear()
            self.samples = self.observations = self.dropped = 0
            self.started_at = None

    # --------------------------------------------------------- poll loop
    def start_sampling(self,
                       interval_s: float | None = None
                       ) -> "SamplingProfiler":
        """Spawn the production sweep driver (daemon thread).  A non-
        positive interval is a no-op, mirroring the watchdog."""
        interval = (self.interval_s if interval_s is None
                    else float(interval_s))
        if interval <= 0 or self._thread is not None:
            return self
        self.interval_s = interval
        self._stop.clear()

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.sample()
                except Exception:   # a broken sweep must not crash
                    traceback.print_exc()   # the process it profiles

        self._thread = threading.Thread(
            target=loop, name="obs-profiler", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
            self._thread = None

    def profile_for(self, seconds: float,
                    clock=time.monotonic) -> "SamplingProfiler":
        """Blocking on-demand window (what ``GET /debug/profile?
        seconds=N`` runs in its handler thread): sweep every
        ``interval_s`` for ``seconds`` (capped at MAX_SECONDS), then
        return self for rendering."""
        seconds = min(max(float(seconds), 0.0), self.MAX_SECONDS)
        interval = self.interval_s if self.interval_s > 0 else 0.01
        end = clock() + seconds
        while clock() < end:
            self.sample()
            time.sleep(interval)
        return self


# process-wide continuous profiler (the serving server installs its own
# here when FLAGS_obs_profile_interval_s > 0, so observability.dump()
# can write profile.json next to the other artifacts)
_ACTIVE: SamplingProfiler | None = None


def active_profiler() -> SamplingProfiler | None:
    return _ACTIVE


def set_active_profiler(profiler: SamplingProfiler | None):
    global _ACTIVE
    _ACTIVE = profiler
    return profiler
