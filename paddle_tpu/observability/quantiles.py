"""Bucket-quantile estimation over Prometheus-style cumulative buckets.

One canonical implementation of "which bucket upper bound crosses the
q-rank" shared by the registry's :class:`Histogram` (``quantile()``),
``tools/metrics_report.py``, and ``tools/fleet_dashboard.py``.  The
estimator is intentionally the conservative Prometheus answer: the
*upper edge* of the cumulative bucket that crosses ``q * count`` (a
``histogram_quantile()`` over the same buckets reports the same edge
for a fully-populated bucket), so p50/p95/p99 read as "at most X".

This module is deliberately import-free: the standalone tools load it
by file path (``importlib.util.spec_from_file_location``) so they keep
their no-paddle_tpu/no-jax contract while sharing the arithmetic.
Buckets are ``(le, cumulative_count)`` pairs with ``le`` a float or
the string ``"+Inf"`` — exactly what ``_HistogramChild.snapshot()``
and a ``metrics.json`` dump carry.
"""
from __future__ import annotations

__all__ = ["bucket_quantiles", "merge_series_buckets",
           "quantile_from_buckets"]

_INF = float("inf")


def _le_key(le):
    return _INF if le == "+Inf" else float(le)


def quantile_from_buckets(buckets, count, q):
    """Upper bucket edge at quantile ``q`` (0 < q <= 1) from cumulative
    ``(le, count)`` pairs totalling ``count`` observations.  Returns a
    float, the string ``"+Inf"`` when the rank lands in the overflow
    bucket, or None when the histogram is empty."""
    if not count or not buckets:
        return None
    rank = q * count
    for le, cum in sorted(buckets, key=lambda kv: _le_key(kv[0])):
        if cum >= rank:
            return le
    return "+Inf"


def bucket_quantiles(buckets, count, qs=(0.5, 0.95, 0.99)):
    """``{q: estimate}`` for each requested quantile (one sort, shared
    by every q)."""
    return {q: quantile_from_buckets(buckets, count, q) for q in qs}


def merge_series_buckets(series):
    """Merge the per-labelset series of one histogram family into one
    cumulative bucket list: takes dicts bearing ``buckets`` /
    ``count`` / ``sum`` (snapshot() output or metrics.json series
    entries) and returns ``(buckets, count, sum)``.  Series with
    mismatched bucket edges merge on the union of edges."""
    per_le: dict = {}
    count, total = 0, 0.0
    for s in series:
        count += s.get("count", 0)
        total += s.get("sum", 0.0)
        prev = 0
        for le, cum in s.get("buckets", []):
            per_le[le] = per_le.get(le, 0) + (cum - prev)
            prev = cum
    acc, merged = 0, []
    for le in sorted(per_le, key=_le_key):
        acc += per_le[le]
        merged.append((le, acc))
    return merged, count, total
