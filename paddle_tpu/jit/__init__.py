"""paddle.jit namespace (reference: python/paddle/jit)."""
from .api import to_static, not_to_static, ignore_module, InputSpec, \
    StaticFunction, enable_to_static
from .serialization import save, load, TranslatedLayer
from .functional import TrainStep, train_step

__all__ = ["to_static", "not_to_static", "ignore_module", "InputSpec",
           "StaticFunction", "enable_to_static", "save", "load",
           "TranslatedLayer", "TrainStep", "train_step"]

_verbosity = 0
_code_level = 0


def set_verbosity(level=0, also_to_stdout=False):
    """(reference jit/dy2static/logging_utils.py set_verbosity): tracing
    here is jax-native, so this records the level for API parity."""
    global _verbosity
    _verbosity = int(level)


def set_code_level(level=100, also_to_stdout=False):
    """(reference jit/dy2static/logging_utils.py set_code_level)."""
    global _code_level
    _code_level = int(level)


__all__ += ["set_verbosity", "set_code_level"]
