"""paddle.jit namespace (reference: python/paddle/jit)."""
from .api import to_static, not_to_static, ignore_module, InputSpec, \
    StaticFunction, enable_to_static
from .serialization import save, load, TranslatedLayer
from .functional import TrainStep, train_step

__all__ = ["to_static", "not_to_static", "ignore_module", "InputSpec",
           "StaticFunction", "enable_to_static", "save", "load",
           "TranslatedLayer", "TrainStep", "train_step"]
