"""paddle.jit.to_static — the dygraph-to-static story on TPU.

Reference: python/paddle/jit/api.py + dy2static/program_translator.py (AST
rewrite + SOT bytecode tracing building a PIR program, cached per input
spec).  Here none of that machinery is needed: every framework op is a pure
jax function, so tracing the user's Python once with the autograd tape
disabled yields the whole program as one jaxpr → one XLA executable.
Control-flow rewriting (AST/SOT) is subsumed by jax tracing; data-dependent
Python branches take the traced path per input-signature cache entry, which
matches SOT's guard-and-specialize behavior.

Training works through the tape: the jitted pure function becomes a single
GradNode via jax.vjp (pjit's transpose is compiled+cached by XLA), so
`loss.backward()` after a to_static forward runs one compiled backward.
"""
from __future__ import annotations

import functools
from typing import Any

import jax
import numpy as np
from jax.tree_util import tree_flatten, tree_unflatten

from ..framework.tensor import Tensor
from ..autograd import tape
from ..framework import random as _random

__all__ = ["to_static", "not_to_static", "ignore_module", "InputSpec",
           "StaticFunction", "enable_to_static"]


class InputSpec:
    """reference: python/paddle/static/input.py InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = list(shape)
        self.dtype = dtype
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


_to_static_enabled = [True]


def enable_to_static(flag=True):
    _to_static_enabled[0] = bool(flag)


def _is_tensor(x):
    return isinstance(x, Tensor)


class StaticFunction:
    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        from ..nn.layer import Layer
        if isinstance(function, Layer):
            self._layer = function
            self._function = function.forward
        else:
            self._layer = getattr(function, "__self__", None)
            if self._layer is not None and not isinstance(self._layer, Layer):
                self._layer = None
            self._function = function
        self._input_spec = input_spec
        self._jit_cache: dict[Any, Any] = {}
        self._converted = False
        functools.update_wrapper(self, self._function)

    # -- helpers ------------------------------------------------------------
    def _state(self):
        if self._layer is None:
            return {}, {}
        params, bufs = {}, {}
        for name, p in self._layer.named_parameters():
            (params if not p.stop_gradient else bufs)[name] = p._data
        for name, b in self._layer.named_buffers():
            bufs["buffers." + name] = b._data
        return params, bufs

    def _make_pure(self, static_key, args_treedef, static_flat,
                   tensor_idx, training):
        layer = self._layer
        fn = self._function

        def pure(params, bufs, key, *tensor_arrays):
            with tape.no_grad(), _random.trace_key_guard(key):
                if layer is not None:
                    saved = layer.functional_state()
                    layer.load_functional_state({**params, **{
                        k: v for k, v in bufs.items()}})
                try:
                    # tensor leaves are traced; every other leaf is baked
                    # in statically (it is part of the cache key), so
                    # python-valued branches stay plain python — the
                    # guard-and-specialize behavior the reference's SOT
                    # gives via bytecode guards
                    wrapped = list(static_flat)
                    for pos, a in zip(tensor_idx, tensor_arrays):
                        wrapped[pos] = Tensor(a, stop_gradient=True)
                    args, kwargs = tree_unflatten(args_treedef, wrapped)
                    out = fn(*args, **kwargs)
                    out_flat, out_tree = tree_flatten(out, is_leaf=_is_tensor)
                    out_arrays = [o._data if isinstance(o, Tensor) else o
                                  for o in out_flat]
                    new_bufs = {}
                    if layer is not None:
                        cur = layer.functional_state()
                        for k in bufs:
                            new_bufs[k] = cur.get(
                                k, cur.get(k.replace("buffers.", ""), bufs[k]))
                    return out_arrays, new_bufs, out_tree
                finally:
                    if layer is not None:
                        layer.load_functional_state(saved)

        # out_tree is static python data — hoist it via a container
        out_tree_box = []

        def pure_arrays_only(params, bufs, key, *tensor_arrays):
            out_arrays, new_bufs, out_tree = pure(params, bufs, key,
                                                  *tensor_arrays)
            if not out_tree_box:
                out_tree_box.append(out_tree)
            return out_arrays, new_bufs

        jitted = jax.jit(pure_arrays_only)
        return jitted, out_tree_box

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled[0]:
            return self._function(*args, **kwargs)
        try:
            return self._call_impl(args, kwargs)
        except (jax.errors.TracerBoolConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.TracerArrayConversionError) as e:
            # tensor-dependent Python control flow: bool tests (`if t:`),
            # `range(traced_n)` (integer/array conversion inside the
            # iterator protocol) — rewrite if/while/for onto
            # lax.cond/lax.while_loop (reference dy2static transformers)
            # and retrace
            if self._converted:
                raise
            self._convert_control_flow(e)
            return self._call_impl(args, kwargs)

    def _convert_control_flow(self, cause):
        import inspect as _inspect
        from .dy2static import convert_to_static_callable, \
            Dy2StUnsupportedError
        fn = self._function
        try:
            if _inspect.ismethod(fn):
                conv = convert_to_static_callable(fn.__func__)
                obj = fn.__self__

                def bound(*a, **k):
                    return conv(obj, *a, **k)
                functools.update_wrapper(bound, fn.__func__)
                self._function = bound
            else:
                self._function = convert_to_static_callable(fn)
        except Dy2StUnsupportedError:
            raise
        except Exception as e:
            raise cause from e
        self._converted = True
        self._jit_cache.clear()

    def _call_impl(self, args, kwargs):
        if self._layer is not None and args and args[0] is self._layer:
            args = args[1:]

        flat, args_treedef = tree_flatten((args, kwargs), is_leaf=_is_tensor)
        # tensors AND array-likes are traced (dynamic); only simple python
        # values — whose repr IS their identity — are baked statically
        def _dynamic(x):
            return isinstance(x, Tensor) or isinstance(x, (np.ndarray,
                                                           jax.Array))
        tensor_idx = [i for i, x in enumerate(flat) if _dynamic(x)]
        tensors = [flat[i] if isinstance(flat[i], Tensor)
                   else Tensor(flat[i], stop_gradient=True)
                   for i in tensor_idx]
        # static key: structure + baked values + dynamic shapes + mode
        training = self._layer.training if self._layer is not None else False
        static_parts = tuple(
            (tuple(np.shape(x._data if isinstance(x, Tensor) else x)),
             str(np.result_type(x._data if isinstance(x, Tensor) else x)))
            if _dynamic(x) else repr(x) for x in flat)
        key = (args_treedef, static_parts, training)

        if key not in self._jit_cache:
            static_flat = [None if _dynamic(x) else x for x in flat]
            self._jit_cache[key] = self._make_pure(
                key, args_treedef, static_flat, tensor_idx, training)
        jitted, out_tree_box = self._jit_cache[key]

        params, bufs = self._state()
        rng = _random.split_key()
        tensor_arrays = [t._data for t in tensors]

        diff_tensors = [t for t in tensors if not t.stop_gradient]
        record = tape.is_grad_enabled() and (
            bool(params) or bool(diff_tensors))

        if not record:
            out_arrays, new_bufs = jitted(params, bufs, rng,
                                          *tensor_arrays)
            self._apply_bufs(new_bufs)
            return self._wrap_out(out_arrays, out_tree_box[0], node=None)

        # differentiate w.r.t. params and diff tensor args
        diff_positions = [i for i, t_ in enumerate(tensors)
                          if not t_.stop_gradient]

        def closed(p, *diff_arrays):
            fa = list(tensor_arrays)
            for pos, a in zip(diff_positions, diff_arrays):
                fa[pos] = a
            return jitted(p, bufs, rng, *fa)

        (out_arrays, new_bufs), raw_vjp = jax.vjp(
            closed, params,
            *[tensor_arrays[i] for i in diff_positions])
        self._apply_bufs(new_bufs)

        out_avals = [jax.ShapeDtypeStruct(np.shape(a), _tan_dtype(a))
                     for a in out_arrays]
        param_tensors = dict(self._layer.named_parameters()) \
            if self._layer is not None else {}
        diff_params = [param_tensors[k] for k in params]
        inputs = diff_params + [tensors[i] for i in diff_positions]

        def vjp_fn(flat_cots):
            cots = (list(flat_cots), _zeros_like_tree(new_bufs))
            pgrads, *agrads = raw_vjp(cots)
            return tuple([pgrads[k] for k in params] + list(agrads))

        node = tape.GradNode(f"to_static:{self._function.__name__}", vjp_fn,
                             inputs, out_avals)
        return self._wrap_out(out_arrays, out_tree_box[0], node=node)

    def _apply_bufs(self, new_bufs):
        if self._layer is None or not new_bufs:
            return
        bufs = dict(self._layer.named_buffers())
        params = dict(self._layer.named_parameters())
        for k, v in new_bufs.items():
            if k.startswith("buffers."):
                bufs[k[len("buffers."):]]._data = v
            elif k in params:
                params[k]._data = v

    def _wrap_out(self, out_arrays, out_tree, node):
        wrapped = []
        for i, a in enumerate(out_arrays):
            diff = node is not None and _tan_dtype(a) != jax.dtypes.float0
            t = Tensor(a, stop_gradient=not diff)
            if diff:
                t._grad_node = node
                t._out_index = i
            wrapped.append(t)
        return tree_unflatten(out_tree, wrapped)

    # concrete program access for save/export
    def get_concrete_program(self, *args, **kwargs):
        return self

    @property
    def code(self):
        import inspect
        return inspect.getsource(self._function)


def _tan_dtype(a):
    dt = np.result_type(a)
    if np.issubdtype(dt, np.inexact) or dt == np.dtype("bfloat16"):
        return dt
    return jax.dtypes.float0


def _zeros_like_tree(tree):
    import jax.numpy as jnp
    return jax.tree_util.tree_map(lambda x: jnp.zeros(x.shape, x.dtype), tree)


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """Decorator/wrapper: compile a function or Layer with XLA."""
    def decorate(fn):
        from ..nn.layer import Layer
        if isinstance(fn, Layer):
            static = StaticFunction(fn, input_spec, build_strategy, backend,
                                    full_graph)
            fn.forward = static
            fn._static_function = static
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    return fn


def ignore_module(modules):
    pass
