"""jit.save / jit.load (reference: python/paddle/jit/api.py save/load →
.json/.pdiparams PIR program format).  The TPU-native serialized program is
StableHLO via jax.export: portable, versioned, loadable without the Python
model code — the same deployment story as the reference's inference format.
"""
from __future__ import annotations

import json
import os
import pickle

import jax
import numpy as np

from ..framework.tensor import Tensor
from ..framework.dtype import to_np_dtype

__all__ = ["save", "load", "TranslatedLayer"]


def save(layer, path, input_spec=None, **configs):
    """Serialize layer: params (.pdiparams), StableHLO program (.stablehlo),
    metadata (.json)."""
    from ..nn.layer import Layer
    from .api import StaticFunction, InputSpec

    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)

    if isinstance(layer, Layer):
        fn = layer.forward
        state = {k: np.asarray(v.numpy()) for k, v in layer.state_dict().items()}
        model = layer
    elif isinstance(layer, StaticFunction):
        fn = layer
        model = layer._layer
        state = {k: np.asarray(v.numpy())
                 for k, v in model.state_dict().items()} if model else {}
    else:
        fn = layer
        model = None
        state = {}

    with open(path + ".pdiparams", "wb") as f:
        pickle.dump(state, f, protocol=4)

    meta = {"format": "paddle_tpu.stablehlo.v1"}
    exported_ok = False
    if input_spec:
        try:
            specs = [jax.ShapeDtypeStruct(
                tuple(1 if s in (-1, None) else s for s in sp.shape),
                to_np_dtype(sp.dtype)) for sp in input_spec]
            was_training = model.training if model is not None else False
            if model is not None:
                model.eval()
            pure = _make_eval_fn(model, fn)
            exp = jax.export.export(jax.jit(pure))(
                {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                 for k, v in state.items()}, *specs)
            with open(path + ".stablehlo", "wb") as f:
                f.write(exp.serialize())
            meta["input_specs"] = [
                {"shape": sp.shape, "dtype": str(sp.dtype)} for sp in input_spec]
            exported_ok = True
            if model is not None and was_training:
                model.train()
        except Exception as e:  # export is best-effort; params always saved
            meta["export_error"] = str(e)
    meta["exported"] = exported_ok
    with open(path + ".json", "w") as f:
        json.dump(meta, f)


def _make_eval_fn(model, fn):
    from ..autograd import tape

    def pure(state, *arrays):
        with tape.no_grad():
            if model is not None:
                saved = model.functional_state()
                merged = dict(saved)
                for k, v in state.items():
                    if k in merged:
                        merged[k] = v
                    elif "buffers." + k in merged:
                        merged["buffers." + k] = v
                model.load_functional_state(merged)
            try:
                inputs = [Tensor(a, stop_gradient=True) for a in arrays]
                call = model.forward if model is not None else fn
                if isinstance(call, object) and hasattr(call, "_function"):
                    call = call._function
                out = call(*inputs)
                if isinstance(out, (list, tuple)):
                    return [o._data if isinstance(o, Tensor) else o for o in out]
                return out._data if isinstance(out, Tensor) else out
            finally:
                if model is not None:
                    model.load_functional_state(saved)

    return pure


class TranslatedLayer:
    """Loaded serialized program (reference: translated_layer.py)."""

    def __init__(self, exported, state):
        self._exported = exported
        self._state = state
        self.training = False

    def __call__(self, *args):
        arrays = [a._data if isinstance(a, Tensor) else np.asarray(a)
                  for a in args]
        out = self._exported.call(self._state, *arrays)
        if isinstance(out, (list, tuple)):
            return [Tensor(o, stop_gradient=True) for o in out]
        return Tensor(out, stop_gradient=True)

    def eval(self):
        return self

    def state_dict(self):
        return {k: Tensor(v) for k, v in self._state.items()}


def load(path, **configs):
    with open(path + ".pdiparams", "rb") as f:
        state = pickle.load(f)
    meta_path = path + ".json"
    hlo_path = path + ".stablehlo"
    if os.path.exists(hlo_path):
        with open(hlo_path, "rb") as f:
            exported = jax.export.deserialize(f.read())
        return TranslatedLayer(exported, state)
    raise FileNotFoundError(
        f"{hlo_path} not found: model was saved without input_spec; "
        "load params via paddle_tpu.load + set_state_dict instead")
