"""AST rewrite of tensor-dependent `if`/`while` into convert_* calls.

Reference: python/paddle/jit/dy2static/transformers/
{ifelse_transformer,loop_transformer,logical_transformer}.py — source-to-
source rewriting so data-dependent Python control flow becomes graph ops.
Here the rewrite targets convert_ifelse/convert_while_loop
(lax.cond / lax.while_loop).

Engaged lazily: to_static first traces the function as-is (plain Python
control flow on concrete values is fine, and is the fast path); only
when tracing raises jax's TracerBoolConversionError does StaticFunction
rebuild the callable through this transformer and retry.

Supported: `if`/`elif`/`else` and `while` whose carried variables are
assigned names (including aug-assign) defined before the statement;
`and`/`or`/`not` inside the tests.  Unsupported (loud errors, matching
the reference's error classes): `return`/`break`/`continue` inside a
converted branch or loop body, and carried values that are neither
tensors nor numeric scalars.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

__all__ = ["convert_to_static_callable", "Dy2StUnsupportedError"]

_PREFIX = "__d2s_"


class Dy2StUnsupportedError(RuntimeError):
    pass


class _NameCollector(ast.NodeVisitor):
    """Names stored anywhere within a statement body."""

    def __init__(self):
        self.stores = []

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                node.id not in self.stores and \
                not node.id.startswith(_PREFIX):
            self.stores.append(node.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # don't descend into nested defs
        if node.name not in self.stores and \
                not node.name.startswith(_PREFIX):
            self.stores.append(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef


def _stored_names(stmts):
    c = _NameCollector()
    for s in stmts:
        c.visit(s)
    return c.stores


class _BanControlEscape(ast.NodeVisitor):
    """Constructs a converted body can't express get loud errors:
    return anywhere, break/continue not owned by a nested loop, and
    attribute/subscript stores (lax.cond traces BOTH branches, so such
    side effects would run unconditionally)."""

    def __init__(self, what):
        self.what = what
        self._loops = 0

    def _ban(self, node, kind):
        raise Dy2StUnsupportedError(
            f"to_static: {kind} inside a tensor-dependent {self.what} "
            "is not convertible to lax control flow; restructure the "
            "function (reference dy2static raises the same class of "
            "error for unsupported rewrites)")

    def visit_Return(self, node):
        self._ban(node, "`return`")

    def visit_Break(self, node):
        if not self._loops:
            self._ban(node, "`break`")

    def visit_Continue(self, node):
        if not self._loops:
            self._ban(node, "`continue`")

    def _visit_loop(self, node):
        self._loops += 1
        self.generic_visit(node)
        self._loops -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop

    def _check_store_target(self, tgt):
        if isinstance(tgt, (ast.Attribute, ast.Subscript)):
            self._ban(tgt, "attribute/subscript assignment (a side "
                           "effect both lax.cond branches would run)")
        for child in ast.iter_child_nodes(tgt):
            self._check_store_target(child)

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._check_store_target(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_store_target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested functions own their control flow

    visit_AsyncFunctionDef = visit_FunctionDef


def _guards(carried, uid):
    """`try: g = name  except (NameError, UnboundLocalError): g = UNDEF`
    per carried name — names first assigned inside the converted body
    enter the carry as UndefinedVar placeholders (reference
    dy2static/utils.py UndefinedVar)."""
    stmts, in_names = [], []
    for j, n in enumerate(carried):
        g = f"{_PREFIX}g{uid}_{j}"
        in_names.append(g)
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[ast.Name(id=g, ctx=ast.Store())],
                             value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(
                    elts=[ast.Name(id="NameError", ctx=ast.Load()),
                          ast.Name(id="UnboundLocalError",
                                   ctx=ast.Load())], ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=g, ctx=ast.Store())],
                    value=ast.Call(
                        func=ast.Name(id=f"{_PREFIX}undef",
                                      ctx=ast.Load()),
                        args=[ast.Constant(value=n)], keywords=[]))])],
            orelse=[], finalbody=[]))
    return stmts, in_names


def _names_load(names):
    return [ast.Name(id=n, ctx=ast.Load()) for n in names]


def _names_store(names):
    return [ast.Name(id=n, ctx=ast.Store()) for n in names]


def _tuple(elts, ctx):
    return ast.Tuple(elts=elts, ctx=ctx)


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    # ---- if / elif / else ------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        carried = sorted(set(_stored_names(node.body)
                             + _stored_names(node.orelse)))
        for stmts in (node.body, node.orelse):
            for s in stmts:
                _BanControlEscape("branch").visit(s)
        uid = self._uid()
        var_arg = f"{_PREFIX}vars"
        carry_tuple_store = _tuple(_names_store(carried), ast.Store())
        carry_tuple_load = _tuple(_names_load(carried), ast.Load())
        guard_stmts, in_names = _guards(carried, uid)
        carry_tuple_in = _tuple(_names_load(in_names), ast.Load())

        def branch_fn(name, stmts):
            body = []
            if carried:
                body.append(ast.Assign(
                    targets=[carry_tuple_store],
                    value=ast.Name(id=var_arg, ctx=ast.Load())))
            body.extend(stmts or [ast.Pass()])
            body.append(ast.Return(value=carry_tuple_load))
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(posonlyargs=[], args=[
                    ast.arg(arg=var_arg)], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=body, decorator_list=[])

        tname = f"{_PREFIX}true_{uid}"
        fname = f"{_PREFIX}false_{uid}"
        call = ast.Call(
            func=ast.Name(id=f"{_PREFIX}convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  carry_tuple_in],
            keywords=[])
        assign = ast.Assign(targets=[carry_tuple_store], value=call) \
            if carried else ast.Expr(value=call)
        return [branch_fn(tname, node.body),
                branch_fn(fname, node.orelse)] + guard_stmts + [assign]

    # ---- while -----------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StUnsupportedError(
                "to_static: while/else is not convertible")
        for s in node.body:
            _BanControlEscape("loop body").visit(s)
        # carry = names stored in the body; read-only names resolve via
        # the nested functions' natural closure over the outer locals
        carried = sorted(set(_stored_names(node.body)))
        uid = self._uid()
        var_arg = f"{_PREFIX}vars"
        carry_store = _tuple(_names_store(carried), ast.Store())
        carry_load = _tuple(_names_load(carried), ast.Load())
        guard_stmts, in_names = _guards(carried, uid)
        carry_in = _tuple(_names_load(in_names), ast.Load())

        def make_fn(name, body_stmts, ret):
            body = [ast.Assign(targets=[carry_store],
                               value=ast.Name(id=var_arg, ctx=ast.Load()))]
            body.extend(body_stmts)
            body.append(ast.Return(value=ret))
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(posonlyargs=[], args=[
                    ast.arg(arg=var_arg)], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=body, decorator_list=[])

        cname = f"{_PREFIX}cond_{uid}"
        bname = f"{_PREFIX}body_{uid}"
        call = ast.Call(
            func=ast.Name(id=f"{_PREFIX}convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  carry_in],
            keywords=[])
        return [make_fn(cname, [], node.test),
                make_fn(bname, list(node.body), carry_load)] \
            + guard_stmts + [ast.Assign(targets=[carry_store], value=call)]

    # ---- boolean operators in tests --------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = f"{_PREFIX}logical_and" if isinstance(node.op, ast.And) \
            else f"{_PREFIX}logical_or"
        out = node.values[0]
        for nxt in node.values[1:]:
            out = ast.Call(
                func=ast.Name(id=op, ctx=ast.Load()),
                args=[ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]), body=out),
                    ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]), body=nxt)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Name(id=f"{_PREFIX}logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node


def convert_to_static_callable(fn):
    """Rebuild `fn` with tensor-dependent if/while rewritten onto
    convert_ifelse/convert_while_loop.  Raises Dy2StUnsupportedError when
    the source can't be obtained or uses unsupported constructs."""
    from . import convert_operators as co

    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise Dy2StUnsupportedError(
            f"to_static: source for {fn!r} unavailable for control-flow "
            "conversion") from e
    tree = ast.parse(src)
    fdef = tree.body[0]
    # strip decorators (e.g. @to_static) so exec defines the plain fn
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        fdef.decorator_list = []
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(getattr(fn, "__globals__", {}))
    if fn.__closure__:
        # freeze free variables as globals (reference rewrites closures
        # similarly; values are captured at conversion time)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError as e:
                raise Dy2StUnsupportedError(
                    f"to_static: free variable {name!r} of {fn.__name__} "
                    "is unbound; cannot convert") from e
    glb[f"{_PREFIX}undef"] = co.UndefinedVar
    glb[f"{_PREFIX}convert_ifelse"] = co.convert_ifelse
    glb[f"{_PREFIX}convert_while"] = co.convert_while_loop
    glb[f"{_PREFIX}logical_and"] = co.convert_logical_and
    glb[f"{_PREFIX}logical_or"] = co.convert_logical_or
    glb[f"{_PREFIX}logical_not"] = co.convert_logical_not

    code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                   mode="exec")
    ns = {}
    exec(code, glb, ns)
    new_fn = ns[fn.__name__]
    functools.update_wrapper(new_fn, fn)
    return new_fn
