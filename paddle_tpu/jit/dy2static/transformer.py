"""AST rewrite of tensor-dependent `if`/`while` into convert_* calls.

Reference: python/paddle/jit/dy2static/transformers/
{ifelse_transformer,loop_transformer,logical_transformer}.py — source-to-
source rewriting so data-dependent Python control flow becomes graph ops.
Here the rewrite targets convert_ifelse/convert_while_loop
(lax.cond / lax.while_loop).

Engaged lazily: to_static first traces the function as-is (plain Python
control flow on concrete values is fine, and is the fast path); only
when tracing raises jax's TracerBoolConversionError does StaticFunction
rebuild the callable through this transformer and retry.

Supported: `if`/`elif`/`else` and `while` whose carried variables are
assigned names (including aug-assign) defined before the statement;
`and`/`or`/`not` inside the tests.  Unsupported (loud errors, matching
the reference's error classes): `return`/`break`/`continue` inside a
converted branch or loop body, and carried values that are neither
tensors nor numeric scalars.
"""
from __future__ import annotations

import ast
import functools
import inspect
import textwrap

__all__ = ["convert_to_static_callable", "Dy2StUnsupportedError"]

_PREFIX = "__d2s_"


class Dy2StUnsupportedError(RuntimeError):
    pass


class _NameCollector(ast.NodeVisitor):
    """Names stored anywhere within a statement body."""

    def __init__(self):
        self.stores = []

    def visit_Name(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)) and \
                node.id not in self.stores and \
                not node.id.startswith(_PREFIX):
            self.stores.append(node.id)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):  # don't descend into nested defs
        if node.name not in self.stores and \
                not node.name.startswith(_PREFIX):
            self.stores.append(node.name)

    visit_AsyncFunctionDef = visit_FunctionDef


def _stored_names(stmts):
    c = _NameCollector()
    for s in stmts:
        c.visit(s)
    return c.stores


class _BanControlEscape(ast.NodeVisitor):
    """Constructs a converted body can't express get loud errors:
    return anywhere, break/continue not owned by a nested loop, and
    attribute/subscript stores (lax.cond traces BOTH branches, so such
    side effects would run unconditionally)."""

    def __init__(self, what):
        self.what = what
        self._loops = 0

    def _ban(self, node, kind):
        raise Dy2StUnsupportedError(
            f"to_static: {kind} inside a tensor-dependent {self.what} "
            "is not convertible to lax control flow; restructure the "
            "function (reference dy2static raises the same class of "
            "error for unsupported rewrites)")

    def visit_Return(self, node):
        self._ban(node, "`return`")

    def visit_Break(self, node):
        if not self._loops:
            self._ban(node, "`break`")

    def visit_Continue(self, node):
        if not self._loops:
            self._ban(node, "`continue`")

    def _visit_loop(self, node):
        self._loops += 1
        self.generic_visit(node)
        self._loops -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop

    def _check_store_target(self, tgt):
        if isinstance(tgt, (ast.Attribute, ast.Subscript)):
            self._ban(tgt, "attribute/subscript assignment (a side "
                           "effect both lax.cond branches would run)")
        for child in ast.iter_child_nodes(tgt):
            self._check_store_target(child)

    def visit_Assign(self, node):
        for tgt in node.targets:
            self._check_store_target(tgt)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check_store_target(node.target)
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass  # nested functions own their control flow

    visit_AsyncFunctionDef = visit_FunctionDef


def _guards(carried, uid):
    """`try: g = name  except (NameError, UnboundLocalError): g = UNDEF`
    per carried name — names first assigned inside the converted body
    enter the carry as UndefinedVar placeholders (reference
    dy2static/utils.py UndefinedVar)."""
    stmts, in_names = [], []
    for j, n in enumerate(carried):
        g = f"{_PREFIX}g{uid}_{j}"
        in_names.append(g)
        stmts.append(ast.Try(
            body=[ast.Assign(targets=[ast.Name(id=g, ctx=ast.Store())],
                             value=ast.Name(id=n, ctx=ast.Load()))],
            handlers=[ast.ExceptHandler(
                type=ast.Tuple(
                    elts=[ast.Name(id="NameError", ctx=ast.Load()),
                          ast.Name(id="UnboundLocalError",
                                   ctx=ast.Load())], ctx=ast.Load()),
                name=None,
                body=[ast.Assign(
                    targets=[ast.Name(id=g, ctx=ast.Store())],
                    value=ast.Call(
                        func=ast.Name(id=f"{_PREFIX}undef",
                                      ctx=ast.Load()),
                        args=[ast.Constant(value=n)], keywords=[]))])],
            orelse=[], finalbody=[]))
    return stmts, in_names


def _names_load(names):
    return [ast.Name(id=n, ctx=ast.Load()) for n in names]


def _names_store(names):
    return [ast.Name(id=n, ctx=ast.Store()) for n in names]


def _tuple(elts, ctx):
    return ast.Tuple(elts=elts, ctx=ctx)


# ---------------------------------------------------------- early exits
# Reference: dy2static/transformers/{return,break_continue,loop}
# _transformer.py — `return`/`break`/`continue` become flag variables +
# guarded remainders, and `for t in range(...)` desugars to `while`, so
# the control-flow conversion below only ever sees straight-line
# if/while bodies.  Flags are ordinary carried names (no _PREFIX, so the
# carry collector threads them through lax.cond/while_loop).

_RET_F, _RET_V = "__rbc_ret_f", "__rbc_ret_v"


def _assign(name, value):
    return ast.Assign(targets=[ast.Name(id=name, ctx=ast.Store())],
                      value=value)


def _const(v):
    return ast.Constant(value=v)


class _EscapeScan(ast.NodeVisitor):
    """Does this statement set an escape flag at the CURRENT level?
    (returns anywhere outside nested defs; break/continue outside
    nested loops)."""

    def __init__(self):
        self.found = False
        self._loops = 0

    def visit_Return(self, node):
        self.found = True

    def visit_Break(self, node):
        if not self._loops:
            self.found = True

    def visit_Continue(self, node):
        if not self._loops:
            self.found = True

    def _loop(self, node):
        self._loops += 1
        self.generic_visit(node)
        self._loops -= 1

    visit_While = _loop
    visit_For = _loop

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _may_escape(stmt):
    s = _EscapeScan()
    s.visit(stmt)
    return s.found


class _HasReturn(ast.NodeVisitor):
    def __init__(self):
        self.found = False

    def visit_Return(self, node):
        self.found = True

    def visit_FunctionDef(self, node):
        pass

    visit_AsyncFunctionDef = visit_FunctionDef


def _not_any(flags):
    """`not (f1 or f2 or ...)` — converted to convert_logical_* later."""
    test = ast.BoolOp(op=ast.Or(),
                      values=_names_load(flags)) if len(flags) > 1 \
        else ast.Name(id=flags[0], ctx=ast.Load())
    return ast.UnaryOp(op=ast.Not(), operand=test)


class _EarlyExitRewriter:
    """Rewrite one function body; self.uses_return reports whether the
    return machinery was installed."""

    def __init__(self):
        self.n_loops = 0
        self.uses_return = False

    def run(self, fdef):
        h = _HasReturn()
        for s in fdef.body:
            h.visit(s)
        self.uses_return = h.found
        body = self._block(list(fdef.body), loop_flags=())
        if self.uses_return:
            # ret_v is NOT pre-initialized: None cannot cross a lax.cond
            # carry; the UndefinedVar guard machinery threads "unset"
            # through converted branches, and the epilogue maps a still-
            # unset slot back to python None (fall-off-the-end path)
            body = [_assign(_RET_F, _const(False))] + body
            epilogue = ast.parse(textwrap.dedent(f"""
                try:
                    __rbc_out = {_RET_V}
                except (NameError, UnboundLocalError):
                    __rbc_out = None
                if isinstance(__rbc_out, {_PREFIX}undef):
                    __rbc_out = None
                return __rbc_out
            """)).body
            body.extend(epilogue)
        fdef.body = body
        return fdef

    # ---- statement lists: guard everything after a possible escape
    def _block(self, stmts, loop_flags):
        out = []
        for i, s in enumerate(stmts):
            escapes = _may_escape(s)
            out.extend(self._stmt(s, loop_flags))
            if escapes and i + 1 < len(stmts):
                rest = self._block(stmts[i + 1:], loop_flags)
                flags = list(loop_flags)
                if self.uses_return:
                    flags.append(_RET_F)
                out.append(ast.If(test=_not_any(flags), body=rest,
                                  orelse=[]))
                break
        return out

    def _stmt(self, s, loop_flags):
        if isinstance(s, ast.Return):
            return [_assign(_RET_V, s.value if s.value is not None
                            else _const(None)),
                    _assign(_RET_F, _const(True))]
        if isinstance(s, ast.Break):
            if not loop_flags:
                raise Dy2StUnsupportedError(
                    "to_static: `break` outside any loop")
            return [_assign(loop_flags[0], _const(True))]
        if isinstance(s, ast.Continue):
            if not loop_flags:
                raise Dy2StUnsupportedError(
                    "to_static: `continue` outside any loop")
            return [_assign(loop_flags[1], _const(True))]
        if isinstance(s, ast.While):
            return self._while(s)
        if isinstance(s, ast.For):
            return self._for(s, loop_flags)
        if isinstance(s, ast.If):
            s.body = self._block(s.body, loop_flags)
            s.orelse = self._block(s.orelse, loop_flags)
            return [s]
        if isinstance(s, (ast.With, ast.Try)):
            for attr in ("body", "orelse", "finalbody"):
                blk = getattr(s, attr, None)
                if blk:
                    setattr(s, attr, self._block(blk, loop_flags))
            return [s]
        return [s]

    def _loop_body(self, node_body, brk, cont):
        """Shared while/for body: reset continue, run the rewritten body
        with this loop's flags as the innermost escape context."""
        body = [_assign(cont, _const(False))]
        body.extend(self._block(node_body, loop_flags=(brk, cont)))
        return body

    def _cond_with_flags(self, test, brk):
        flags = [brk] + ([_RET_F] if self.uses_return else [])
        return ast.BoolOp(op=ast.And(), values=[_not_any(flags), test])

    def _while(self, node):
        if node.orelse:
            raise Dy2StUnsupportedError(
                "to_static: while/else is not convertible")
        self.n_loops += 1
        brk = f"__rbc_brk{self.n_loops}"
        cont = f"__rbc_cont{self.n_loops}"
        new = ast.While(test=self._cond_with_flags(node.test, brk),
                        body=self._loop_body(node.body, brk, cont),
                        orelse=[])
        return [_assign(brk, _const(False)),
                _assign(cont, _const(False)), new]

    def _for(self, node, loop_flags):
        """`for i in range(...)` -> while (traced bounds become
        lax.while_loop); any other iterable keeps the python loop
        (static unroll under trace) with flag-guarded body."""
        if node.orelse:
            raise Dy2StUnsupportedError(
                "to_static: for/else is not convertible")
        self.n_loops += 1
        brk = f"__rbc_brk{self.n_loops}"
        cont = f"__rbc_cont{self.n_loops}"
        is_range = (isinstance(node.iter, ast.Call)
                    and isinstance(node.iter.func, ast.Name)
                    and node.iter.func.id == "range"
                    and not node.iter.keywords
                    and 1 <= len(node.iter.args) <= 3
                    and isinstance(node.target, ast.Name))
        prolog = [_assign(brk, _const(False)),
                  _assign(cont, _const(False))]
        if is_range:
            uid = self.n_loops      # snapshot: _loop_body may nest loops
            a = node.iter.args
            start = _const(0) if len(a) == 1 else a[0]
            stop = a[0] if len(a) == 1 else a[1]
            step = a[2] if len(a) == 3 else _const(1)
            if not (isinstance(step, ast.Constant)
                    and isinstance(step.value, int) and step.value != 0):
                raise Dy2StUnsupportedError(
                    "to_static: for-range needs a non-zero constant "
                    "int step")
            i = node.target.id
            ctr = f"__rbc_i{uid}"
            cmp_op = ast.Lt() if step.value > 0 else ast.Gt()
            test = ast.Compare(
                left=ast.Name(id=ctr, ctx=ast.Load()), ops=[cmp_op],
                comparators=[ast.Name(id=f"__rbc_stop{uid}",
                                      ctx=ast.Load())])
            # an internal counter drives the loop; the user's variable is
            # assigned from it at the TOP of each iteration, so after the
            # loop (or a break) it holds the last ENTERED value — python
            # for-semantics, not one-step-high
            body = [_assign(i, ast.Name(id=ctr, ctx=ast.Load()))]
            body.extend(self._loop_body(node.body, brk, cont))
            body.append(ast.AugAssign(
                target=ast.Name(id=ctr, ctx=ast.Store()), op=ast.Add(),
                value=_const(step.value)))
            return prolog + [
                _assign(ctr, start),
                # prolog init types the lax.while carry; each iteration
                # re-assigns from the counter (python for-semantics)
                _assign(i, ast.Name(id=ctr, ctx=ast.Load())),
                _assign(f"__rbc_stop{uid}", stop),
                ast.While(test=self._cond_with_flags(test, brk),
                          body=body, orelse=[])]
        # generic iterable: python-level loop, flag-guarded iterations
        guard_flags = [brk] + ([_RET_F] if self.uses_return else [])
        body = [ast.If(test=_not_any(guard_flags),
                       body=self._loop_body(node.body, brk, cont),
                       orelse=[])]
        return prolog + [ast.For(target=node.target, iter=node.iter,
                                 body=body, orelse=[])]


class _ControlFlowTransformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0

    def _uid(self):
        self.counter += 1
        return self.counter

    # ---- if / elif / else ------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        carried = sorted(set(_stored_names(node.body)
                             + _stored_names(node.orelse)))
        for stmts in (node.body, node.orelse):
            for s in stmts:
                _BanControlEscape("branch").visit(s)
        uid = self._uid()
        var_arg = f"{_PREFIX}vars"
        carry_tuple_store = _tuple(_names_store(carried), ast.Store())
        carry_tuple_load = _tuple(_names_load(carried), ast.Load())
        guard_stmts, in_names = _guards(carried, uid)
        carry_tuple_in = _tuple(_names_load(in_names), ast.Load())

        def branch_fn(name, stmts):
            body = []
            if carried:
                body.append(ast.Assign(
                    targets=[carry_tuple_store],
                    value=ast.Name(id=var_arg, ctx=ast.Load())))
            body.extend(stmts or [ast.Pass()])
            body.append(ast.Return(value=carry_tuple_load))
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(posonlyargs=[], args=[
                    ast.arg(arg=var_arg)], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=body, decorator_list=[])

        tname = f"{_PREFIX}true_{uid}"
        fname = f"{_PREFIX}false_{uid}"
        call = ast.Call(
            func=ast.Name(id=f"{_PREFIX}convert_ifelse", ctx=ast.Load()),
            args=[node.test,
                  ast.Name(id=tname, ctx=ast.Load()),
                  ast.Name(id=fname, ctx=ast.Load()),
                  carry_tuple_in],
            keywords=[])
        assign = ast.Assign(targets=[carry_tuple_store], value=call) \
            if carried else ast.Expr(value=call)
        return [branch_fn(tname, node.body),
                branch_fn(fname, node.orelse)] + guard_stmts + [assign]

    # ---- while -----------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse:
            raise Dy2StUnsupportedError(
                "to_static: while/else is not convertible")
        for s in node.body:
            _BanControlEscape("loop body").visit(s)
        # carry = names stored in the body; read-only names resolve via
        # the nested functions' natural closure over the outer locals
        carried = sorted(set(_stored_names(node.body)))
        uid = self._uid()
        var_arg = f"{_PREFIX}vars"
        carry_store = _tuple(_names_store(carried), ast.Store())
        carry_load = _tuple(_names_load(carried), ast.Load())
        guard_stmts, in_names = _guards(carried, uid)
        carry_in = _tuple(_names_load(in_names), ast.Load())

        def make_fn(name, body_stmts, ret):
            body = [ast.Assign(targets=[carry_store],
                               value=ast.Name(id=var_arg, ctx=ast.Load()))]
            body.extend(body_stmts)
            body.append(ast.Return(value=ret))
            return ast.FunctionDef(
                name=name,
                args=ast.arguments(posonlyargs=[], args=[
                    ast.arg(arg=var_arg)], kwonlyargs=[], kw_defaults=[],
                    defaults=[]),
                body=body, decorator_list=[])

        cname = f"{_PREFIX}cond_{uid}"
        bname = f"{_PREFIX}body_{uid}"
        call = ast.Call(
            func=ast.Name(id=f"{_PREFIX}convert_while", ctx=ast.Load()),
            args=[ast.Name(id=cname, ctx=ast.Load()),
                  ast.Name(id=bname, ctx=ast.Load()),
                  carry_in],
            keywords=[])
        return [make_fn(cname, [], node.test),
                make_fn(bname, list(node.body), carry_load)] \
            + guard_stmts + [ast.Assign(targets=[carry_store], value=call)]

    # ---- boolean operators in tests --------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        op = f"{_PREFIX}logical_and" if isinstance(node.op, ast.And) \
            else f"{_PREFIX}logical_or"
        out = node.values[0]
        for nxt in node.values[1:]:
            out = ast.Call(
                func=ast.Name(id=op, ctx=ast.Load()),
                args=[ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]), body=out),
                    ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[],
                                       kwonlyargs=[], kw_defaults=[],
                                       defaults=[]), body=nxt)],
                keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.Call(
                func=ast.Name(id=f"{_PREFIX}logical_not", ctx=ast.Load()),
                args=[node.operand], keywords=[])
        return node


def convert_to_static_callable(fn):
    """Rebuild `fn` with tensor-dependent if/while rewritten onto
    convert_ifelse/convert_while_loop.  Raises Dy2StUnsupportedError when
    the source can't be obtained or uses unsupported constructs."""
    from . import convert_operators as co

    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError) as e:
        raise Dy2StUnsupportedError(
            f"to_static: source for {fn!r} unavailable for control-flow "
            "conversion") from e
    tree = ast.parse(src)
    fdef = tree.body[0]
    # strip decorators (e.g. @to_static) so exec defines the plain fn
    if isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        fdef.decorator_list = []
        # pass 1: return/break/continue -> flags, for-range -> while
        _EarlyExitRewriter().run(fdef)
    # pass 2: tensor-dependent if/while -> lax control flow
    new_tree = _ControlFlowTransformer().visit(tree)
    ast.fix_missing_locations(new_tree)

    glb = dict(getattr(fn, "__globals__", {}))
    if fn.__closure__:
        # freeze free variables as globals (reference rewrites closures
        # similarly; values are captured at conversion time)
        for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
            try:
                glb[name] = cell.cell_contents
            except ValueError as e:
                raise Dy2StUnsupportedError(
                    f"to_static: free variable {name!r} of {fn.__name__} "
                    "is unbound; cannot convert") from e
    glb[f"{_PREFIX}undef"] = co.UndefinedVar
    glb[f"{_PREFIX}convert_ifelse"] = co.convert_ifelse
    glb[f"{_PREFIX}convert_while"] = co.convert_while_loop
    glb[f"{_PREFIX}logical_and"] = co.convert_logical_and
    glb[f"{_PREFIX}logical_or"] = co.convert_logical_or
    glb[f"{_PREFIX}logical_not"] = co.convert_logical_not

    code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                   mode="exec")
    ns = {}
    exec(code, glb, ns)
    new_fn = ns[fn.__name__]
    functools.update_wrapper(new_fn, fn)
    return new_fn
