"""dy2static: control-flow conversion for to_static.

Reference package: python/paddle/jit/dy2static/ (AST transformers +
convert_operators).  The SOT bytecode JIT is unnecessary on this
architecture (jax traces Python directly; see jit/api.py), but
tensor-dependent `if`/`while` still need real conversion — provided
here by convert_operators over lax.cond/lax.while_loop and a
source-level transformer engaged when plain tracing fails.
"""
from .convert_operators import (  # noqa: F401
    convert_ifelse, convert_while_loop, convert_logical_and,
    convert_logical_or, convert_logical_not, convert_len, convert_shape,
    to_static_variable)
from .transformer import (  # noqa: F401
    convert_to_static_callable, Dy2StUnsupportedError)

__all__ = ["convert_ifelse", "convert_while_loop", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "convert_len",
           "convert_shape", "to_static_variable",
           "convert_to_static_callable", "Dy2StUnsupportedError"]
