"""Control-flow conversion primitives for to_static.

Reference: python/paddle/jit/dy2static/convert_operators.py —
convert_ifelse / convert_while_loop / convert_logical_* route
tensor-dependent Python control flow into graph ops (cond_op/while_op).
TPU-native: the same API shape lowers onto `lax.cond` /
`lax.while_loop`, the XLA-compilable control-flow primitives; concrete
(non-traced) predicates keep plain Python semantics.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.tensor import Tensor

__all__ = ["convert_ifelse", "convert_while_loop", "convert_logical_and",
           "convert_logical_or", "convert_logical_not", "convert_len",
           "convert_shape", "to_static_variable", "UndefinedVar", "UNDEF"]


class UndefinedVar:
    """Placeholder for names not yet bound when a converted statement
    runs (reference dy2static/utils.py UndefinedVar).  Any real use
    raises; it can still ride through a cond/while carry as a dummy."""

    def __init__(self, name="<var>"):
        self.name = name

    def _die(self, *_a, **_k):
        raise NameError(
            f"variable {self.name!r} is used before being assigned on "
            "every path of a converted tensor-dependent if/while")

    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _die
    __truediv__ = __rtruediv__ = __matmul__ = __getitem__ = _die
    __call__ = __bool__ = __float__ = __int__ = __iter__ = _die
    __lt__ = __le__ = __gt__ = __ge__ = _die


UNDEF = UndefinedVar()


def _is_traced(x):
    if isinstance(x, Tensor):
        x = x._data
    return isinstance(x, jax.core.Tracer)


def _pred_value(pred):
    if isinstance(pred, Tensor):
        pred = pred._data
    return pred


def _pack(vals):
    """Tensors/scalars -> arrays; UndefinedVar -> dummy scalar (its spec
    entry keeps the sentinel so _unpack restores it untouched)."""
    arrs, spec = [], []
    for v in vals:
        if isinstance(v, UndefinedVar):
            arrs.append(jnp.zeros((), jnp.float32))
            spec.append(v)
        elif isinstance(v, Tensor):
            arrs.append(v._data)
            spec.append("tensor")
        elif isinstance(v, (bool, int, float)) or _is_traced(v) or \
                hasattr(v, "dtype"):
            arrs.append(jnp.asarray(v))
            spec.append("array")
        else:
            raise TypeError(
                f"control-flow carried value of type {type(v).__name__} "
                "cannot cross a lax.cond/while_loop boundary; only "
                "tensors and numeric scalars can")
    return tuple(arrs), spec


def _unpack(arrs, spec):
    out = []
    for a, s in zip(arrs, spec):
        out.append(s if isinstance(s, UndefinedVar)
                   else Tensor(a, stop_gradient=True))
    return tuple(out)




def _resolve_guarded_slots(arrs, spec, branch_fns, allow_all=False):
    """Slots holding the return/break machinery's value registers
    (__rbc_*) may be assigned on only SOME paths; every READ of them is
    flag-guarded by construction, so the unassigned side can carry a
    typed zero.  Abstractly probe the branch fns and seed such slots
    with zeros of the assigned side's aval (reference return_transformer
    RETURN_NO_VALUE placeholder).

    allow_all=True (the while/for path) extends this to USER names first
    assigned inside the loop body — e.g. a desugared nested for-range's
    target, whose prolog init lives inside the outer loop's body.  The
    reference loop_transformer fills such names with typed placeholders
    the same way; the cost is that a ZERO-trip loop leaves them 0 rather
    than raising NameError.  `if` branches keep the loud error for user
    names (assign-on-both-paths is the readable contract there)."""
    guarded = [j for j, sp in enumerate(spec)
               if isinstance(sp, UndefinedVar)
               and (allow_all or str(sp.name).startswith("__rbc_"))]
    if not guarded:
        return arrs, spec
    probes = []
    for fn in branch_fns:
        mask_box = []

        def run(arrs_, _fn=fn, _box=mask_box):
            out = _fn(_unpack(arrs_, spec))
            if not isinstance(out, tuple):
                out = (out,)
            oa, osp = _pack(out)
            # concrete at trace time; must not ride eval_shape's outputs
            _box.append([isinstance(x, UndefinedVar) for x in osp])
            return oa
        try:
            oa_shapes = jax.eval_shape(run, arrs)
        except Exception:
            return arrs, spec          # let the real call surface errors
        probes.append((oa_shapes, mask_box[0]))
    arrs = list(arrs)
    spec = list(spec)
    for j in guarded:
        assigned = [sh[j] for sh, mask in probes if not mask[j]]
        if assigned:
            aval = assigned[0]
            arrs[j] = jnp.zeros(aval.shape, aval.dtype)
            spec[j] = "array"
    return tuple(arrs), spec


def convert_ifelse(pred, true_fn, false_fn, vars_tuple):
    """`out_vars = convert_ifelse(pred, tfn, ffn, vars)` — reference
    convert_operators.py convert_ifelse.  true_fn/false_fn take and
    return the tuple of carried variables."""
    p = _pred_value(pred)
    if not _is_traced(p):
        return true_fn(vars_tuple) if bool(p) else false_fn(vars_tuple)

    arrs, spec = _pack(vars_tuple)
    arrs, spec = _resolve_guarded_slots(arrs, spec, (true_fn, false_fn))
    out_specs = {}

    def wrap(fn, tag):
        def run(arrs):
            out = fn(_unpack(arrs, spec))
            if not isinstance(out, tuple):
                out = (out,)
            out_arrs, out_spec = _pack(out)
            out_specs[tag] = out_spec
            return out_arrs
        return run

    pred_arr = jnp.reshape(jnp.asarray(p), ()).astype(bool)

    def _undef_mismatch():
        for a, b in zip(out_specs.get("t", ()), out_specs.get("f", ())):
            if isinstance(a, UndefinedVar) != isinstance(b, UndefinedVar):
                return a.name if isinstance(a, UndefinedVar) else b.name
        return None

    try:
        out_arrs = jax.lax.cond(pred_arr, wrap(true_fn, "t"),
                                wrap(false_fn, "f"), arrs)
    except TypeError as e:
        name = _undef_mismatch()
        if name is not None:
            raise NameError(
                f"variable {name!r} is assigned in only one branch of a "
                "tensor-dependent if; assign it on both paths (or "
                "before the if) so the converted lax.cond has a value "
                "either way") from e
        raise
    name = _undef_mismatch()
    if name is not None:
        raise NameError(
            f"variable {name!r} is assigned in only one branch of a "
            "tensor-dependent if; assign it on both paths (or before "
            "the if) so the converted lax.cond has a value either way")
    return _unpack(out_arrs, out_specs["t"])


def convert_while_loop(cond_fn, body_fn, vars_tuple):
    """`out_vars = convert_while_loop(cond, body, vars)` — reference
    convert_operators.py convert_while_loop over lax.while_loop."""
    probe = cond_fn(vars_tuple)
    p = _pred_value(probe)
    if not _is_traced(p) and not any(
            _is_traced(v) for v in vars_tuple):
        # fully concrete: plain Python loop
        while bool(_pred_value(cond_fn(vars_tuple))):
            vars_tuple = body_fn(vars_tuple)
            if not isinstance(vars_tuple, tuple):
                vars_tuple = (vars_tuple,)
        return vars_tuple

    arrs, spec = _pack(vars_tuple)
    arrs, spec = _resolve_guarded_slots(arrs, spec, (body_fn,),
                                        allow_all=True)
    out_spec_box = []

    def cond(arrs):
        c = _pred_value(cond_fn(_unpack(arrs, spec)))
        return jnp.reshape(jnp.asarray(c), ()).astype(bool)

    def body(arrs):
        out = body_fn(_unpack(arrs, spec))
        if not isinstance(out, tuple):
            out = (out,)
        out_arrs, out_spec = _pack(out)
        if not out_spec_box:
            out_spec_box.append(out_spec)
        return out_arrs

    try:
        out_arrs = jax.lax.while_loop(cond, body, arrs)
    except TypeError as e:
        undef = [sp.name for sp in spec if isinstance(sp, UndefinedVar)]
        if undef:
            raise NameError(
                f"variables {undef} are first assigned inside a "
                "tensor-dependent while; initialize them before the loop "
                "so the converted lax.while_loop carry is well-typed")                 from e
        raise
    return _unpack(out_arrs, out_spec_box[0] if out_spec_box else spec)


def convert_logical_and(x_fn, y_fn):
    x = x_fn()
    xv = _pred_value(x)
    if not _is_traced(xv):
        return y_fn() if bool(xv) else x
    y = _pred_value(y_fn())
    return Tensor(jnp.logical_and(jnp.asarray(xv).astype(bool),
                                  jnp.asarray(y).astype(bool)),
                  stop_gradient=True)


def convert_logical_or(x_fn, y_fn):
    x = x_fn()
    xv = _pred_value(x)
    if not _is_traced(xv):
        return x if bool(xv) else y_fn()
    y = _pred_value(y_fn())
    return Tensor(jnp.logical_or(jnp.asarray(xv).astype(bool),
                                 jnp.asarray(y).astype(bool)),
                  stop_gradient=True)


def convert_logical_not(x):
    xv = _pred_value(x)
    if not _is_traced(xv):
        return not bool(xv)
    return Tensor(jnp.logical_not(jnp.asarray(xv).astype(bool)),
                  stop_gradient=True)


def convert_len(x):
    if isinstance(x, Tensor):
        return x.shape[0]
    return len(x)


def convert_shape(x):
    return x.shape


def to_static_variable(x):
    return x
