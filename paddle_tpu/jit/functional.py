"""Compiled training step: the TPU hot path.

The reference's static-graph training (Executor over a PIR program with
fused kernels) maps to a single jitted function of
(params, opt_state, batch, key) -> (loss, params, opt_state): forward,
backward, and optimizer update fused into one XLA executable, parameters
donated so updates happen in-place in HBM.

`TrainStep` drives a stock `nn.Layer` + `optimizer.Optimizer` through this
path without the user rewriting anything: it re-runs the tape under trace
(all op bodies are pure jax) and captures the optimizer's state pytree.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor
from ..framework import random as _random
from ..autograd import tape

__all__ = ["TrainStep", "train_step"]


class TrainStep:
    def __init__(self, model, optimizer, loss_fn: Callable, donate=True):
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self._compiled = None
        self._donate = donate

    def _build(self):
        return jax.jit(self._pure_step(), donate_argnums=(
            (0, 2) if self._donate else ()))

    def _pure_step(self):
        """The unjitted (params, bufs, opt_state, key, *batch) ->
        (loss, params, bufs, opt_state) function — scannable."""
        model, optimizer, loss_fn = self.model, self.optimizer, self.loss_fn

        def step(params, bufs, opt_state, key, *batch):
            with _random.trace_key_guard(key):
                # load traced state into the live objects
                saved = model.functional_state()
                model.load_functional_state({**params, **bufs})
                optimizer.load_opt_state(opt_state)
                param_objs = {name: p for name, p in model.named_parameters()}
                try:
                    inputs = jax.tree.map(
                        lambda a: Tensor(a, stop_gradient=True), list(batch))
                    with tape.enable_grad():
                        loss = loss_fn(model, *inputs)
                        loss.backward()
                    optimizer.step()
                    optimizer.clear_grad()
                    new_params = {k: param_objs[k]._data for k in params}
                    new_bufs = {k: v for k, v in model.functional_state().items()
                                if k in bufs}
                    new_opt = optimizer.opt_state()
                    return loss._data, new_params, new_bufs, new_opt
                finally:
                    model.load_functional_state(saved)

        return step

    def multi_step(self, n):
        """Compile an n-step training scan: ONE device dispatch runs n
        optimizer steps on the same batch argument (pass fresh batches
        per call for real epochs).  This amortizes per-dispatch latency
        — essential on tunneled/remote device transports where each
        dispatch costs tens of ms — mirroring how the reference's
        Executor replays a whole program per run call.

            many = paddle.jit.train_step(model, opt, loss_fn).multi_step(10)
            loss = many(x, y)     # 10 steps, one dispatch
        """
        pure = self._pure_step()

        def many(params, bufs, opt_state, key, *batch):
            keys = jax.random.split(key, n)
            # step 1 runs unrolled: it materializes lazily-created
            # optimizer accumulators so the scan carry is structure-stable
            loss0, p, b_, o = pure(params, bufs, opt_state, keys[0],
                                   *batch)
            if n == 1:
                return loss0, p, b_, o

            def body(carry, k):
                p, b_, o = carry
                loss, p2, b2, o2 = pure(p, b_, o, k, *batch)
                return (p2, b2, o2), loss

            (p, b_, o), losses = jax.lax.scan(body, (p, b_, o), keys[1:])
            return losses[-1], p, b_, o

        jitted = jax.jit(many, donate_argnums=(
            (0, 2) if self._donate else ()))
        outer = self

        def run(*batch):
            params = {k: p._data for k, p in
                      outer.model.named_parameters()}
            bufs = {"buffers." + k: b._data
                    for k, b in outer.model.named_buffers()}
            opt_state = outer.optimizer.opt_state()
            key = _random.split_key()
            loss, new_params, new_bufs, new_opt = jitted(
                params, bufs, opt_state, key, *_as_arrays(batch))
            outer.model.load_functional_state({**new_params, **new_bufs})
            outer.optimizer.load_opt_state(new_opt)
            return Tensor(loss, stop_gradient=True)

        return run

    def __call__(self, *batch):
        """Run one compiled step; returns the loss Tensor."""
        if self._compiled is None:
            self._compiled = self._build()
        model, optimizer = self.model, self.optimizer
        params = {}
        bufs = {}
        for name, p in model.named_parameters():
            params[name] = p._data
        for name, b in model.named_buffers():
            bufs["buffers." + name] = b._data
        opt_state = optimizer.opt_state()
        key = _random.split_key()
        # batch items may be arbitrary pytrees (tuples/dicts from a
        # DataLoader); Tensors become raw arrays at the leaves
        arrays = _as_arrays(batch)
        loss, new_params, new_bufs, new_opt = self._compiled(
            params, bufs, opt_state, key, *arrays)
        # write results back into the live objects
        model.load_functional_state({**new_params, **new_bufs})
        optimizer.load_opt_state(new_opt)
        if optimizer._lr_scheduler is not None:
            pass  # user steps the scheduler per paddle convention
        return Tensor(loss, stop_gradient=True)


def _as_arrays(batch):
    return jax.tree.map(
        lambda b: b._data if isinstance(b, Tensor) else jnp.asarray(b),
        list(batch), is_leaf=lambda b: isinstance(b, Tensor))


def train_step(model, optimizer, loss_fn):
    """Build a compiled train step:

        step = paddle_tpu.jit.train_step(model, opt,
                    lambda m, x, y: F.cross_entropy(m(x), y))
        loss = step(x_batch, y_batch)
    """
    return TrainStep(model, optimizer, loss_fn)
