"""paddle.linalg namespace (reference: python/paddle/linalg.py re-exports)."""
from .ops.linalg import (  # noqa: F401
    matmul, mm, bmm, mv, t, einsum, norm, vector_norm, matrix_norm, dist,
    cholesky, cholesky_solve, qr, svd, svdvals, pca_lowrank, inv, pinv, det,
    slogdet, solve, triangular_solve, lstsq, lu, eig, eigh, eigvals,
    eigvalsh, matrix_power, matrix_rank, cond, corrcoef, cov,
    householder_product, matrix_exp, cholesky_inverse, lu_unpack,
    multi_dot, ormqr, svd_lowrank, fp8_fp8_half_gemm_fused)
from .ops.math import cross, dot  # noqa: F401
