"""Autoregressive generation with a static-shape KV cache.

Reference analog: the decoding stack the reference exposes through
fused inference ops (paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu, masked_multihead_attention) and
PaddleNLP's generate() loop.

TPU formulation: the whole decode is ONE jitted program —
  * prefill: full-sequence forward over the (right-padded) prompt fills
    a kv-head-major [L, B, kvH, T, D] cache; prompt lengths are data,
    shapes are static.
  * decode: `lax.scan` over max_new_tokens, each step one-token
    attention against the cache (dot-products on the MXU, no [S,S]
    materialization); the per-batch cache write is a positional
    compare-and-select (positions differ per row, so a plain
    dynamic_update_slice does not apply).
  * sampling: greedy / temperature / top-k / top-p, all shape-static
    (top-p via sorted-cumsum masking).
No Python-loop-per-token, no retrace per step, no dynamic shapes.
"""
from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from .llama import LlamaConfig, _rope_tables, _rotate_half
from .llama_hybrid import _rms

__all__ = ["GenerationConfig", "generate", "build_generate_fn",
           "quantize_state"]

_FN_CACHE: dict = {}   # (config fields, prompt_len, gen fields) -> jitted fn
_FN_CACHE_MAX = 16


def astuple_cfg(cfg):
    """Value-based cache key: id(cfg) can be reused after GC."""
    import dataclasses
    return tuple(sorted(dataclasses.asdict(cfg).items()))


@dataclass
class GenerationConfig:
    max_new_tokens: int = 64
    do_sample: bool = False
    temperature: float = 1.0
    top_k: int = 0
    top_p: float = 1.0
    eos_token_id: int | None = None
    pad_token_id: int = 0
    seed: int = 0


# ------------------------------------------------------------- weight view
def _mm(h, w):
    """Matmul against a raw weight, a legacy ``(int8, scale)`` pair, or
    a :class:`~paddle_tpu.ops.pallas.quant_matmul.QuantizedWeight`.

    The quantized path runs the Pallas weight-only GEMV kernel at
    decode shapes (int8 tiles stream HBM->VMEM, dequant in-register,
    per-channel scale fused on the f32 accumulator — the reference
    weight_only_gemv.cu role); prefill-shaped calls and off-TPU
    backends take the XLA dequant-into-matmul path inside
    weight_only_matmul."""
    from ..ops.pallas.quant_matmul import QuantizedWeight, weight_only_matmul
    if isinstance(w, tuple):        # legacy (int8, scale) pair
        w = QuantizedWeight(w[0], w[1], kind="int8")
    if isinstance(w, QuantizedWeight):
        return weight_only_matmul(h, w)
    return h @ w


_QUANT_KEYS = ("self_attn.q_proj.weight", "self_attn.k_proj.weight",
               "self_attn.v_proj.weight", "self_attn.o_proj.weight",
               "mlp.gate_proj.weight", "mlp.up_proj.weight",
               "mlp.down_proj.weight")


def quantize_state(state, algo="weight_only_int8"):
    """Replace every matmul weight in a generation state dict with a
    :class:`QuantizedWeight` (embeddings stay dense: they are gathers,
    not matmuls).  int4 weights are nibble-packed [K/2, N] — a quarter
    of the bf16 HBM footprint.

    q/k/v and gate/up are quantized FUSED (columns concatenated before
    per-output-channel quantization — bit-identical to separate, since
    the scale is per column) so the decode loop issues one GEMV kernel
    where it issued three: at B=8 decode shapes the launch count, not
    the flops, is the cost.  Contract: the per-projection q/k/v and
    gate/up keys are ALSO quantized individually, so every matmul key
    in the returned dict is a QuantizedWeight — consumers reading the
    per-projection keys directly (instead of the *_fused entries the
    decode loop prefers) still get the quantized path.  The reference
    analog is converting a deploy model through weight_quantize before
    serving (python/paddle/nn/quant)."""
    from ..nn.quant import weight_quantize
    from ..ops.pallas.quant_matmul import QuantizedWeight

    kind = "int4" if algo.endswith("int4") else "int8"

    def quant(arr):
        q, scale = weight_quantize.__op_body__(arr, algo)
        return QuantizedWeight(q, scale, kind=kind, k=arr.shape[0])

    out = dict(state)
    for name in state:
        p, _, leaf = name.rpartition(".self_attn.q_proj.weight")
        if leaf == "" and p:
            pre = p + ".self_attn."
            out[pre + "qkv_fused.weight"] = quant(jnp.concatenate(
                [state[pre + "q_proj.weight"],
                 state[pre + "k_proj.weight"],
                 state[pre + "v_proj.weight"]], axis=1))
        p, _, leaf = name.rpartition(".mlp.gate_proj.weight")
        if leaf == "" and p:
            pre = p + ".mlp."
            out[pre + "gateup_fused.weight"] = quant(jnp.concatenate(
                [state[pre + "gate_proj.weight"],
                 state[pre + "up_proj.weight"]], axis=1))
    for name, arr in state.items():
        if name.endswith(_QUANT_KEYS) or name == "lm_head.weight":
            # fused members included: the returned state is UNIFORMLY
            # quantized (r4 advisor: a consumer reading q_proj.weight
            # directly must not silently run dense)
            out[name] = quant(arr)
    return out


def _layer_weights(state, i):
    p = f"llama.layers.{i}."
    w = {
        "ln1": state[p + "input_layernorm.weight"],
        "q": state[p + "self_attn.q_proj.weight"],
        "k": state[p + "self_attn.k_proj.weight"],
        "v": state[p + "self_attn.v_proj.weight"],
        "o": state[p + "self_attn.o_proj.weight"],
        "ln2": state[p + "post_attention_layernorm.weight"],
        "gate": state[p + "mlp.gate_proj.weight"],
        "up": state[p + "mlp.up_proj.weight"],
        "down": state[p + "mlp.down_proj.weight"],
    }
    if p + "self_attn.qkv_fused.weight" in state:   # quantized serving
        w["qkv"] = state[p + "self_attn.qkv_fused.weight"]
    if p + "mlp.gateup_fused.weight" in state:
        w["gateup"] = state[p + "mlp.gateup_fused.weight"]
    return w


def _qkv_proj(w, h, nh, kvh, hd, lora=(), aidx=None, li=0):
    """(q, k, v) projections — one fused GEMV when the quantized state
    provides it, three matmuls otherwise.  A non-empty ``lora`` bank
    adds each slot's rank-r adapter delta on top (``aidx`` indexes the
    bank per row; ``lora=()`` is the dense path, byte-identical jaxpr
    — zero extra pytree leaves, no traced ops)."""
    if "qkv" in w:
        qkv = _mm(h, w["qkv"])
        q, k, v = (qkv[..., :nh * hd], qkv[..., nh * hd:(nh + kvh) * hd],
                   qkv[..., (nh + kvh) * hd:])
    else:
        q, k, v = _mm(h, w["q"]), _mm(h, w["k"]), _mm(h, w["v"])
    if lora:
        from ..ops.pallas.lora_matmul import lora_delta
        q = q + lora_delta(lora, "q", li, h, aidx)
        k = k + lora_delta(lora, "k", li, h, aidx)
        v = v + lora_delta(lora, "v", li, h, aidx)
    return q, k, v


def _ffn(w, h, lora=(), aidx=None, li=0):
    if "gateup" in w:
        gu = _mm(h, w["gateup"])
        half = gu.shape[-1] // 2
        g, u = gu[..., :half], gu[..., half:]
    else:
        g, u = _mm(h, w["gate"]), _mm(h, w["up"])
    if lora:
        from ..ops.pallas.lora_matmul import lora_delta
        g = g + lora_delta(lora, "gate", li, h, aidx)
        u = u + lora_delta(lora, "up", li, h, aidx)
    act = jax.nn.silu(g) * u
    out = _mm(act, w["down"])
    if lora:
        out = out + lora_delta(lora, "down", li, act, aidx)
    return out


def _rope_at(cos, sin, pos):
    """cos/sin: [max_len, D]; pos: [...] -> [..., D]"""
    return jnp.take(cos, pos, axis=0), jnp.take(sin, pos, axis=0)


# ---------------------------------------------------------------- prefill
def _prefill_layer(w, x, cos, sin, mask, cfg: LlamaConfig, lora=(),
                   aidx=None, li=0):
    """x: [B, S, H]; returns (out, k_cache, v_cache [B, S, kvH, D])."""
    b, s, _ = x.shape
    nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    h = _rms(x, w["ln1"], cfg.rms_norm_eps)
    qp, kp, vp = _qkv_proj(w, h, nh, kvh, hd, lora, aidx, li)
    q = qp.reshape(b, s, nh, hd)
    k = kp.reshape(b, s, kvh, hd)
    v = vp.reshape(b, s, kvh, hd)
    cos_c = cos[None, :, None, :].astype(q.dtype)
    sin_c = sin[None, :, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    # flash path: causal + key-padding mask, GQA in-kernel, O(S) memory
    # (the naive [B,H,S,S] fp32 logits OOM long-prompt prefill)
    from ..ops.pallas.flash_attention import sdpa
    attn = sdpa(q, k, v, attn_mask=mask[:, None, None, :],
                is_causal=True).reshape(b, s, nh * hd)
    o = _mm(attn, w["o"])
    if lora:
        from ..ops.pallas.lora_matmul import lora_delta
        o = o + lora_delta(lora, "o", li, attn, aidx)
    x = x + o
    h = _rms(x, w["ln2"], cfg.rms_norm_eps)
    return (x + _ffn(w, h, lora, aidx, li), k, v)


# ------------------------------------------------------------ decode step
def _decode_layer(w, x, kcache, vcache, cos1, sin1, pos, cfg: LlamaConfig):
    """x: [B, H] one token; kcache/vcache: [B, kvH, T, D] (kv-head-major,
    the decode kernel's tiling-friendly layout); pos: [B]."""
    b = x.shape[0]
    nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    h = _rms(x[:, None], w["ln1"], cfg.rms_norm_eps)[:, 0]
    qp, kp, vp = _qkv_proj(w, h, nh, kvh, hd)
    q = qp.reshape(b, nh, hd)
    k = kp.reshape(b, kvh, hd)
    v = vp.reshape(b, kvh, hd)
    cos_c = cos1[:, None, :].astype(q.dtype)
    sin_c = sin1[:, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    # write this token's k/v at pos (per-batch positions).  A scatter —
    # NOT a compare-select over the whole cache: jnp.where materializes
    # a full cache copy per layer per step (~268 MB of HBM traffic at
    # the bench shapes), while .at[].set lowers to an in-place update
    # of one token row on the donated scan carry
    b_ids = jnp.arange(b)
    kcache = kcache.at[b_ids, :, pos, :].set(k, mode="drop")
    vcache = vcache.at[b_ids, :, pos, :].set(v, mode="drop")

    # blockwise cache attention kernel (ops/pallas/decode_attention.py);
    # transparently falls back to the einsum path off-TPU
    from ..ops.pallas.decode_attention import decode_attention
    attn = decode_attention(q, kcache, vcache, pos).reshape(b, nh * hd)
    x = x + _mm(attn, w["o"])
    h = _rms(x[:, None], w["ln2"], cfg.rms_norm_eps)[:, 0]
    return (x + _ffn(w, h), kcache, vcache)


# ------------------------------------------------------- paged decode step
def _decode_layer_paged(w, x, kpool, vpool, table, cos1, sin1, pos,
                        cfg: LlamaConfig, lora=(), aidx=None, li=0):
    """Paged-cache decode layer: pools [P, kvH, ps, D], table
    [B, max_pages]; pos [B] is the CURRENT token's position.  The
    write targets page table[b, pos // ps] slot pos % ps — always a
    real reserved page; reads go through the paged kernel (reference
    block_multi_head_attention_kernel.cu)."""
    b = x.shape[0]
    nh, kvh, hd = (cfg.num_attention_heads, cfg.num_key_value_heads,
                   cfg.head_dim)
    ps = kpool.shape[2]
    h = _rms(x[:, None], w["ln1"], cfg.rms_norm_eps)[:, 0]
    qp, kp, vp = _qkv_proj(w, h, nh, kvh, hd, lora, aidx, li)
    q = qp.reshape(b, nh, hd)
    k = kp.reshape(b, kvh, hd)
    v = vp.reshape(b, kvh, hd)
    cos_c = cos1[:, None, :].astype(q.dtype)
    sin_c = sin1[:, None, :].astype(q.dtype)
    q = q * cos_c + _rotate_half(q) * sin_c
    k = k * cos_c + _rotate_half(k) * sin_c

    page = jnp.take_along_axis(table, (pos // ps)[:, None], axis=1)[:, 0]
    off = pos % ps
    heads = jnp.arange(kvh)
    kpool = kpool.at[page[:, None], heads[None, :], off[:, None]].set(k)
    vpool = vpool.at[page[:, None], heads[None, :], off[:, None]].set(v)

    from ..ops.pallas.paged_attention import select_paged_attention
    attn = select_paged_attention()(
        q, kpool, vpool, table, pos + 1).reshape(b, nh * hd)
    o = _mm(attn, w["o"])
    if lora:
        from ..ops.pallas.lora_matmul import lora_delta
        o = o + lora_delta(lora, "o", li, attn, aidx)
    x = x + o
    h = _rms(x[:, None], w["ln2"], cfg.rms_norm_eps)[:, 0]
    g = _mm(h, w["gate"])
    u = _mm(h, w["up"])
    if lora:
        g = g + lora_delta(lora, "gate", li, h, aidx)
        u = u + lora_delta(lora, "up", li, h, aidx)
    act = jax.nn.silu(g) * u
    d = _mm(act, w["down"])
    if lora:
        d = d + lora_delta(lora, "down", li, act, aidx)
    return (x + d, kpool, vpool)


# --------------------------------------------------------------- sampling
def _sample(logits, key, gen: GenerationConfig):
    logits = logits.astype(jnp.float32)
    if not gen.do_sample:
        return jnp.argmax(logits, axis=-1)
    if gen.temperature != 1.0:
        logits = logits / jnp.float32(max(gen.temperature, 1e-6))
    if gen.top_k and gen.top_k > 0:
        k = min(gen.top_k, logits.shape[-1])
        kth = jnp.sort(logits, axis=-1)[..., -k][..., None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if gen.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with cumulative prob >= top_p
        cutoff_idx = jnp.sum(cum < gen.top_p, axis=-1, keepdims=True)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1)


# ------------------------------------------------------------ paged main
def build_generate_fn_paged(config: LlamaConfig, gen: GenerationConfig,
                            prompt_len: int, page_size: int,
                            num_pages: int, max_pages: int):
    """Paged-cache generate: jitted (state, ids, lengths, key, table) ->
    tokens.  Pools are allocated inside (zeros) with static shapes from
    the PagedPool reservation; HBM scales with sum(len+new), not
    B * max_len (reference block_multi_head_attention serving path)."""
    L = config.num_hidden_layers
    kvh, hd = config.num_key_value_heads, config.head_dim
    T = prompt_len + gen.max_new_tokens
    assert T <= config.max_position_embeddings
    ps = page_size
    prompt_pages = -(-prompt_len // ps)

    def run(state, ids, lengths, key, table):
        b = ids.shape[0]
        dtype = state["llama.embed_tokens.weight"].dtype
        rope_len = max(T, prompt_pages * ps)
        cos, sin = _rope_tables(rope_len, config.head_dim,
                                config.rope_theta)
        cos = cos.astype(jnp.float32)
        sin = sin.astype(jnp.float32)

        kpool = jnp.zeros((L, num_pages, kvh, ps, hd), dtype)
        vpool = jnp.zeros((L, num_pages, kvh, ps, hd), dtype)

        # ---- prefill over the padded prompt, paging k/v into the pool
        x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
        pmask = jnp.arange(prompt_len)[None, :] < lengths[:, None]
        spad = prompt_pages * ps - prompt_len
        for i in range(L):
            w = _layer_weights(state, i)
            x, k, v = _prefill_layer(w, x, cos[:prompt_len],
                                     sin[:prompt_len], pmask, config)
            kp = jnp.pad(k, ((0, 0), (0, spad), (0, 0), (0, 0)))
            vp = jnp.pad(v, ((0, 0), (0, spad), (0, 0), (0, 0)))
            for p in range(prompt_pages):
                rows_k = kp[:, p * ps:(p + 1) * ps].swapaxes(1, 2)
                rows_v = vp[:, p * ps:(p + 1) * ps].swapaxes(1, 2)
                kpool = kpool.at[i, table[:, p]].set(rows_k)
                vpool = vpool.at[i, table[:, p]].set(rows_v)

        x = _rms(x, state["llama.norm.weight"], config.rms_norm_eps)
        head = state.get("lm_head.weight")

        def logits_of(h):
            if head is not None:
                return _mm(h, head)
            return h @ state["llama.embed_tokens.weight"].T

        last = jnp.take_along_axis(
            x, (lengths - 1)[:, None, None].astype(jnp.int32),
            axis=1)[:, 0]
        key, sub = jax.random.split(key)
        tok = _sample(logits_of(last), sub, gen)

        done = jnp.zeros((b,), bool)
        if gen.eos_token_id is not None:
            done = done | (tok == gen.eos_token_id)

        def step(carry, key_t):
            tok, pos, kpool, vpool, done = carry
            emb = jnp.take(state["llama.embed_tokens.weight"], tok,
                           axis=0)
            cos1, sin1 = _rope_at(cos, sin, pos)
            h = emb
            kps, vps = [], []
            for i in range(L):
                w = _layer_weights(state, i)
                h, kp_, vp_ = _decode_layer_paged(
                    w, h, kpool[i], vpool[i], table, cos1, sin1, pos,
                    config)
                kps.append(kp_)
                vps.append(vp_)
            kpool = jnp.stack(kps)
            vpool = jnp.stack(vps)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     config.rms_norm_eps)[:, 0]
            nxt = _sample(logits_of(h), key_t, gen)
            if gen.eos_token_id is not None:
                nxt = jnp.where(done, gen.pad_token_id, nxt)
                done = done | (nxt == gen.eos_token_id)
            return (nxt, pos + 1, kpool, vpool, done), tok

        keys = jax.random.split(key, gen.max_new_tokens)
        (tok, _, _, _, _), toks = jax.lax.scan(
            step, (tok.astype(ids.dtype), lengths.astype(jnp.int32),
                   kpool, vpool, done), keys)
        return jnp.concatenate([ids, toks.T.astype(ids.dtype)], axis=1)

    return jax.jit(run)


# ------------------------------------------------------------------ main
def _cache_len(prompt_len, max_new_tokens):
    """Padded cache length: the block-cache kernel needs 128 alignment
    (rope rows past max_position_embeddings exist but are never
    addressed); the XLA path skips it so tiny caches stay tiny."""
    from ..ops.pallas import decode_attention as _DA
    T = prompt_len + max_new_tokens
    if _DA.PALLAS_DECODE or _DA._INTERPRET:
        T = -(-T // 128) * 128
    return T


def _prefill_prompt(state, ids, lengths, cos, sin, config, prompt_len, T):
    """Shared prompt prefill (greedy + beam paths): returns
    (last [B, D] hidden of each prompt's final real token, logits_of,
    kcache [L, B, kvH, T, D], vcache)."""
    L = config.num_hidden_layers
    x = jnp.take(state["llama.embed_tokens.weight"], ids, axis=0)
    pmask = jnp.arange(prompt_len)[None, :] < lengths[:, None]
    kcaches, vcaches = [], []
    for i in range(L):
        w = _layer_weights(state, i)
        x, k, v = _prefill_layer(w, x, cos[:prompt_len],
                                 sin[:prompt_len], pmask, config)
        # kv-head-major cache layout [B, kvH, T, D]
        pad = ((0, 0), (0, 0), (0, T - prompt_len), (0, 0))
        kcaches.append(jnp.pad(k.swapaxes(1, 2), pad))
        vcaches.append(jnp.pad(v.swapaxes(1, 2), pad))
    kcache = jnp.stack(kcaches)
    vcache = jnp.stack(vcaches)

    x = _rms(x, state["llama.norm.weight"], config.rms_norm_eps)
    head = state.get("lm_head.weight")

    def logits_of(h):
        if head is not None:
            return _mm(h, head)
        return h @ state["llama.embed_tokens.weight"].T

    last = jnp.take_along_axis(
        x, (lengths - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    return last, logits_of, kcache, vcache


def build_generate_fn(config: LlamaConfig, gen: GenerationConfig,
                      prompt_len: int):
    """Returns jitted (state, ids[B, prompt_len], lengths[B], key) ->
    tokens [B, prompt_len + max_new_tokens]."""
    L = config.num_hidden_layers
    T = _cache_len(prompt_len, gen.max_new_tokens)
    assert prompt_len + gen.max_new_tokens \
        <= config.max_position_embeddings

    def run(state, ids, lengths, key):
        b = ids.shape[0]
        cos, sin = _rope_tables(T, config.head_dim, config.rope_theta)
        cos = cos.astype(jnp.float32)
        sin = sin.astype(jnp.float32)

        last, logits_of, kcache, vcache = _prefill_prompt(
            state, ids, lengths, cos, sin, config, prompt_len, T)
        key, sub = jax.random.split(key)
        tok = _sample(logits_of(last), sub, gen)

        done = jnp.zeros((b,), bool)
        if gen.eos_token_id is not None:
            done = done | (tok == gen.eos_token_id)

        def step(carry, key_t):
            tok, pos, kcache, vcache, done = carry
            emb = jnp.take(state["llama.embed_tokens.weight"], tok, axis=0)
            cos1, sin1 = _rope_at(cos, sin, pos)
            h = emb
            newk, newv = [], []
            for i in range(L):
                w = _layer_weights(state, i)
                h, kc, vc = _decode_layer(w, h, kcache[i], vcache[i],
                                          cos1, sin1, pos, config)
                newk.append(kc)
                newv.append(vc)
            kcache = jnp.stack(newk)
            vcache = jnp.stack(newv)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     config.rms_norm_eps)[:, 0]
            nxt = _sample(logits_of(h), key_t, gen)
            if gen.eos_token_id is not None:
                nxt = jnp.where(done, gen.pad_token_id, nxt)
                done = done | (nxt == gen.eos_token_id)
            return (nxt, pos + 1, kcache, vcache, done), tok

        keys = jax.random.split(key, gen.max_new_tokens)
        (tok, _, _, _, _), toks = jax.lax.scan(
            step, (tok.astype(ids.dtype), lengths.astype(jnp.int32),
                   kcache, vcache, done), keys)
        # toks[t] is the token sampled after t decode steps: exactly
        # max_new_tokens new tokens (the final carry is one beyond)
        return jnp.concatenate([ids, toks.T.astype(ids.dtype)], axis=1)

    return jax.jit(run)


def build_generate_fn_beam(config: LlamaConfig, gen: GenerationConfig,
                           prompt_len: int, num_beams: int):
    """Beam-search decoding with the KV cache (reference
    nn/decode.py BeamSearchDecoder semantics over the serving engine):
    fixed-shape [B, K, V] top-k merge per step under jax.lax.scan, beam
    ancestry resolved by a gather_tree backtrace — no ragged hypothesis
    sets, everything jits.  Finished beams emit only eos with log-prob 0
    (score freezes), matching the reference's noend mask."""
    L = config.num_hidden_layers
    K = num_beams
    T = _cache_len(prompt_len, gen.max_new_tokens)
    assert prompt_len + gen.max_new_tokens \
        <= config.max_position_embeddings
    eos = gen.eos_token_id

    def run(state, ids, lengths, key):
        b = ids.shape[0]
        cos, sin = _rope_tables(T, config.head_dim, config.rope_theta)
        cos = cos.astype(jnp.float32)
        sin = sin.astype(jnp.float32)

        last, logits_of, kcache, vcache = _prefill_prompt(
            state, ids, lengths, cos, sin, config, prompt_len, T)
        lp0 = jax.nn.log_softmax(
            logits_of(last).astype(jnp.float32), axis=-1)   # [B, V]
        V = lp0.shape[-1]
        # first step: top-K over the vocab seeds the beams
        log_probs, tok = jax.lax.top_k(lp0, K)              # [B, K]
        done = jnp.zeros((b, K), bool)
        if eos is not None:
            done = done | (tok == eos)

        # beams share the prefill cache: expand to [L, B*K, kvh, T, D]
        def expand(c):
            return jnp.repeat(c, K, axis=1)

        kcache, vcache = expand(kcache), expand(vcache)
        noend = jnp.full((V,), -1e9, jnp.float32)
        if eos is not None:
            noend = noend.at[eos].set(0.0)

        def step(carry, _):
            tok, pos, kcache, vcache, log_probs, done = carry
            flat_tok = tok.reshape(b * K)
            emb = jnp.take(state["llama.embed_tokens.weight"], flat_tok,
                           axis=0)
            posf = jnp.repeat(pos, K)
            cos1, sin1 = _rope_at(cos, sin, posf)
            h = emb
            newk, newv = [], []
            for i in range(L):
                w = _layer_weights(state, i)
                h, kc, vc = _decode_layer(w, h, kcache[i], vcache[i],
                                          cos1, sin1, posf, config)
                newk.append(kc)
                newv.append(vc)
            kcache = jnp.stack(newk)
            vcache = jnp.stack(newv)
            h = _rms(h[:, None], state["llama.norm.weight"],
                     config.rms_norm_eps)[:, 0]
            step_lp = jax.nn.log_softmax(
                logits_of(h).astype(jnp.float32), axis=-1) \
                .reshape(b, K, V)
            # finished beams: only eos continues, at zero cost
            step_lp = jnp.where(done[:, :, None], noend[None, None, :],
                                step_lp)
            cand = (log_probs[:, :, None] + step_lp).reshape(b, K * V)
            log_probs, flat_idx = jax.lax.top_k(cand, K)     # [B, K]
            parent = flat_idx // V
            nxt = flat_idx % V

            # reorder beam state by ancestry
            gidx = (jnp.arange(b)[:, None] * K + parent).reshape(-1)
            kcache = kcache[:, gidx]
            vcache = vcache[:, gidx]
            done = jnp.take_along_axis(done, parent, axis=1)
            if eos is not None:
                nxt = jnp.where(done, gen.pad_token_id, nxt)
                done = done | (nxt == eos)
            return ((nxt, pos + 1, kcache, vcache, log_probs, done),
                    (tok, parent))

        init = (tok.astype(jnp.int32), lengths.astype(jnp.int32),
                kcache, vcache, log_probs, done)
        (tok, _, _, _, log_probs, _), (toks, parents) = jax.lax.scan(
            step, init, None, length=gen.max_new_tokens - 1)
        # toks[t]: tokens in time-t beam order; scan's parent_j maps
        # time-(j+1) beams to time-j beams, so toks[t] pairs with
        # parents[t-1] — the seed row (t=0) has identity ancestry
        toks = jnp.concatenate([toks, tok[None]], axis=0)   # [N, B, K]
        parents = jnp.concatenate(
            [jnp.broadcast_to(jnp.arange(K), (1, b, K)), parents], axis=0)

        # backtrace ancestry (nn.functional gather_tree semantics)
        def bt(carry, inp):
            beam = carry
            t_tok, t_par = inp
            out = jnp.take_along_axis(t_tok, beam, axis=-1)
            beam = jnp.take_along_axis(t_par, beam, axis=-1)
            return beam, out

        init_beam = jnp.broadcast_to(jnp.arange(K), (b, K))
        _, seq_rev = jax.lax.scan(bt, init_beam,
                                  (toks[::-1], parents[::-1]))
        seqs = seq_rev[::-1]                                # [N, B, K]
        best = jnp.argmax(log_probs, axis=-1)               # [B]
        best_seq = jnp.take_along_axis(
            seqs, best[None, :, None], axis=2)[:, :, 0].T   # [B, N]
        return jnp.concatenate([ids, best_seq.astype(ids.dtype)], axis=1)

    return jax.jit(run)


def generate(model, input_ids, max_new_tokens=64, do_sample=False,
             temperature=1.0, top_k=0, top_p=1.0, eos_token_id=None,
             pad_token_id=0, seed=0, lengths=None, cache="dense",
             page_size=128, weight_quant=None, num_beams=1):
    """User entry: model is a LlamaForCausalLM; input_ids [B, S] (right-
    padded if lengths given; new tokens overwrite the padded slots in the
    cache). Returns [B, S + max_new_tokens] ids.

    cache="paged" serves from a block-table pool (reference
    block_multi_head_attention): HBM and attention reads scale with each
    sequence's OWN length instead of the batch max — the win on ragged
    batches.

    num_beams > 1 runs beam search (reference nn/decode.py semantics)
    with the dense KV cache — a fixed-shape [B, K, V] top-k merge per
    scanned step."""
    from ..framework.tensor import Tensor

    ids = input_ids._data if isinstance(input_ids, Tensor) else \
        jnp.asarray(input_ids)
    b, s = ids.shape
    if lengths is None:
        lengths_np = np.full((b,), s, np.int32)
    else:
        lengths_np = np.asarray(
            lengths._data if isinstance(lengths, Tensor) else lengths,
            np.int32)
    lengths_arr = jnp.asarray(lengths_np)
    gen = GenerationConfig(
        max_new_tokens=max_new_tokens, do_sample=do_sample,
        temperature=temperature, top_k=top_k, top_p=top_p,
        eos_token_id=eos_token_id, pad_token_id=pad_token_id, seed=seed)
    state = {k: (v._data if isinstance(v, Tensor) else v)
             for k, v in model.functional_state().items()}
    if weight_quant is not None:
        if weight_quant not in ("int8", "int4"):
            raise ValueError(f"weight_quant must be int8|int4, "
                             f"got {weight_quant!r}")
        # quantize once per (model weights, algo): serving loops call
        # generate() per request and must not re-quantize every call.
        # Keyed by identity of the source arrays (held strongly in the
        # cache, so ids cannot be reused); rebinding any weight (a
        # training step) misses and re-quantizes.
        wq_cache = getattr(model, "_wq_cache", None)
        src = {k: v for k, v in state.items()
               if k.endswith(_QUANT_KEYS) or k == "lm_head.weight"}
        if (wq_cache is not None and wq_cache["algo"] == weight_quant
                and wq_cache["src"].keys() == src.keys()
                and all(wq_cache["src"][k] is v for k, v in src.items())):
            qstate = wq_cache["state"]
        else:
            qstate = quantize_state(state, f"weight_only_{weight_quant}")
            model._wq_cache = {"algo": weight_quant, "src": src,
                               "state": qstate}
        # carry the quantized leaves AND the fused qkv/gateup entries
        state = dict(state, **{k: v for k, v in qstate.items()
                               if k in src
                               or k.endswith(("qkv_fused.weight",
                                              "gateup_fused.weight"))})
    from ..ops.pallas import decode_attention as _DA

    if num_beams > 1:
        if do_sample:
            raise ValueError("num_beams > 1 requires do_sample=False "
                             "(beam search is deterministic)")
        if cache == "paged":
            raise NotImplementedError(
                "beam search currently uses the dense cache "
                "(paged-beam reordering needs per-beam block tables)")
        cache_key = ("beam", astuple_cfg(model.config), s,
                     gen.max_new_tokens, num_beams, gen.eos_token_id,
                     gen.pad_token_id,
                     _DA.PALLAS_DECODE or _DA._INTERPRET, weight_quant)
        fn = _FN_CACHE.get(cache_key)
        if fn is None:
            if len(_FN_CACHE) >= _FN_CACHE_MAX:
                _FN_CACHE.pop(next(iter(_FN_CACHE)))
            fn = _FN_CACHE[cache_key] = build_generate_fn_beam(
                model.config, gen, s, num_beams)
        out = fn(state, ids, lengths_arr, jax.random.key(seed))
        return Tensor(out, stop_gradient=True)

    if cache == "paged":
        from ..ops.pallas.paged_attention import PagedPool
        pool = PagedPool(lengths_np, gen.max_new_tokens,
                         page_size=page_size,
                         min_table_width=-(-s // page_size))
        cache_key = ("paged", astuple_cfg(model.config), s,
                     gen.max_new_tokens, gen.do_sample, gen.temperature,
                     gen.top_k, gen.top_p, gen.eos_token_id,
                     gen.pad_token_id, pool.page_size, pool.num_pages,
                     pool.max_pages, weight_quant)
        fn = _FN_CACHE.get(cache_key)
        if fn is None:
            if len(_FN_CACHE) >= _FN_CACHE_MAX:
                _FN_CACHE.pop(next(iter(_FN_CACHE)))
            fn = _FN_CACHE[cache_key] = build_generate_fn_paged(
                model.config, gen, s, pool.page_size, pool.num_pages,
                pool.max_pages)
        out = fn(state, ids, lengths_arr, jax.random.key(seed),
                 jnp.asarray(pool.table))
        return Tensor(out, stop_gradient=True)

    cache_key = (astuple_cfg(model.config), s,
                 gen.max_new_tokens, gen.do_sample, gen.temperature,
                 gen.top_k, gen.top_p, gen.eos_token_id, gen.pad_token_id,
                 _DA.PALLAS_DECODE or _DA._INTERPRET, weight_quant)
    fn = _FN_CACHE.get(cache_key)
    if fn is None:
        if len(_FN_CACHE) >= _FN_CACHE_MAX:   # bound compiled programs
            _FN_CACHE.pop(next(iter(_FN_CACHE)))
        fn = _FN_CACHE[cache_key] = build_generate_fn(
            model.config, gen, s)
    out = fn(state, ids, lengths_arr, jax.random.key(seed))
    return Tensor(out, stop_gradient=True)
