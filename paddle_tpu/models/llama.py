"""Llama-family decoder LM, TPU-first.

Reference: the in-tree auto-parallel Llama test model
(test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py:93,121,195
— LlamaAttention/LlamaMLP/LlamaDecoderLayer built from dist.shard_tensor)
and the fused transformer ops it exercises
(python/paddle/incubate/nn/functional/fused_rms_norm.py, flash attention
paddle/phi/kernels/gpu/flash_attn_kernel.cu).

TPU design choices:
  * attention runs through ops.pallas.flash_attention.sdpa (Pallas blockwise
    kernel on TPU, flash-reference XLA fallback elsewhere); GQA native.
  * rotary embedding precomputed once per forward in fp32, applied in
    input dtype — keeps the MXU in bf16.
  * weights are plain nn.Linear ([in, out]); tensor parallelism is applied
    as GSPMD shardings via `llama_tp_shard_fn` (the reference's colwise /
    rowwise placements), NOT via distinct layer classes — the same model
    object runs 1-chip or N-D-mesh unchanged.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import jax.numpy as jnp

from .. import nn
from ..nn import functional as F
from ..framework.tensor import Tensor
from ..ops.pallas.flash_attention import sdpa


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    recompute: bool = False
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def llama3_8b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=128256, hidden_size=4096, intermediate_size=14336,
        num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
        max_position_embeddings=8192, rope_theta=500000.0, dtype="bfloat16")


def llama_tiny(**kw) -> LlamaConfig:
    cfg = dict(vocab_size=512, hidden_size=128, intermediate_size=256,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=2, max_position_embeddings=256)
    cfg.update(kw)
    return LlamaConfig(**cfg)


class LlamaRMSNorm(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.hidden_size = config.hidden_size
        self.weight = self.create_parameter(
            [config.hidden_size],
            default_initializer=nn.initializer.Constant(1.0))
        self.variance_epsilon = config.rms_norm_eps

    def forward(self, x):
        return F.rms_norm(x, self.weight, epsilon=self.variance_epsilon)


def _rope_tables(seq_len, head_dim, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32)
                           / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv)                       # [S, D/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [S, D]
    return jnp.cos(emb).astype(dtype), jnp.sin(emb).astype(dtype)


def _rotate_half(x):
    h = x.shape[-1] // 2
    return jnp.concatenate([-x[..., h:], x[..., :h]], axis=-1)


def apply_rotary_pos_emb(q, k, cos, sin):
    """q,k: [B, S, H, D]; cos,sin: [S, D] (fp32 tables, applied in dtype)."""
    cos = cos[None, :, None, :].astype(q.dtype)
    sin = sin[None, :, None, :].astype(q.dtype)
    return q * cos + _rotate_half(q) * sin, k * cos + _rotate_half(k) * sin


class LlamaAttention(nn.Layer):
    """GQA attention (reference test model LlamaAttention:93)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        h, hd = config.hidden_size, config.head_dim
        kvh = config.num_key_value_heads
        self.num_heads = config.num_attention_heads
        self.num_key_value_heads = kvh
        self.head_dim = hd
        self.q_proj = nn.Linear(h, self.num_heads * hd, bias_attr=False)
        self.k_proj = nn.Linear(h, kvh * hd, bias_attr=False)
        self.v_proj = nn.Linear(h, kvh * hd, bias_attr=False)
        self.o_proj = nn.Linear(self.num_heads * hd, h, bias_attr=False)

    def forward(self, hidden_states, attn_mask=None, cos=None, sin=None):
        b, s, _ = hidden_states.shape
        q = self.q_proj(hidden_states).reshape(
            [b, s, self.num_heads, self.head_dim])
        k = self.k_proj(hidden_states).reshape(
            [b, s, self.num_key_value_heads, self.head_dim])
        v = self.v_proj(hidden_states).reshape(
            [b, s, self.num_key_value_heads, self.head_dim])
        if cos is None:
            cos, sin = _rope_tables(s, self.head_dim, self.config.rope_theta)
            cos, sin = Tensor(cos), Tensor(sin)
        q, k = rope_op(q, k, cos, sin)
        # causal always: attn_mask (e.g. padding) composes with, never
        # replaces, the causal structure of the LM
        out = flash_attention(q, k, v, attn_mask, is_causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        return self.o_proj(out)


class LlamaMLP(nn.Layer):
    """SwiGLU FFN (reference test model LlamaMLP:121)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        h, i = config.hidden_size, config.intermediate_size
        self.gate_proj = nn.Linear(h, i, bias_attr=False)
        self.up_proj = nn.Linear(h, i, bias_attr=False)
        self.down_proj = nn.Linear(i, h, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = LlamaRMSNorm(config)
        self.post_attention_layernorm = LlamaRMSNorm(config)

    def forward(self, hidden_states, attn_mask=None, cos=None, sin=None):
        residual = hidden_states
        h = self.input_layernorm(hidden_states)
        h = self.self_attn(h, attn_mask=attn_mask, cos=cos, sin=sin)
        h = residual + h
        residual = h
        h = self.post_attention_layernorm(h)
        h = self.mlp(h)
        return residual + h


class LlamaModel(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = nn.Embedding(config.vocab_size,
                                         config.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = LlamaRMSNorm(config)

    def forward(self, input_ids, attn_mask=None):
        h = self.embed_tokens(input_ids)
        if self.config.dtype == "bfloat16":
            h = h.astype("bfloat16")
        s = input_ids.shape[1]
        cos, sin = _rope_tables(s, self.config.head_dim,
                                self.config.rope_theta)
        cos, sin = Tensor(cos), Tensor(sin)
        from ..distributed.fleet import recompute as _rc
        for layer in self.layers:
            if self.config.recompute and self.training:
                h = _rc.recompute(layer, h, attn_mask, cos, sin)
            else:
                h = layer(h, attn_mask=attn_mask, cos=cos, sin=sin)
        return self.norm(h)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                     bias_attr=False)

        if config.dtype == "bfloat16":
            self.bfloat16()

    def forward(self, input_ids, attn_mask=None):
        h = self.llama(input_ids, attn_mask=attn_mask)
        if self.lm_head is None:
            w = self.llama.embed_tokens.weight
            return h.matmul(w, transpose_y=True)
        return self.lm_head(h)

    def generate(self, input_ids, **kwargs):
        """KV-cache autoregressive decoding (models/generation.py)."""
        from .generation import generate as _generate
        return _generate(self, input_ids, **kwargs)


class LlamaPretrainingCriterion(nn.Layer):
    """Shifted next-token cross entropy in fp32 (reference test model's
    criterion; loss math must leave bf16)."""

    def forward(self, logits, labels):
        logits = logits.astype("float32")
        return F.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]),
            labels.reshape([-1]), reduction="mean")


# ---------------------------------------------------------------- sharding
def llama_tp_shard_fn(mesh, tp_axis="tp", dp_axis=None):
    """shard_fn for dist.shard_layer implementing the reference's TP plan
    (semi_auto_parallel_llama_model.py: colwise q/k/v/gate/up Shard(1),
    rowwise o/down Shard(0), embedding Shard(1) on its hidden dim;
    everything else replicated).  Returns (name, layer, mesh) -> None."""
    from ..distributed.placement import Shard, Replicate
    from ..distributed.auto_parallel.api import shard_tensor

    col = ("q_proj", "k_proj", "v_proj", "gate_proj", "up_proj", "lm_head")
    row = ("o_proj", "down_proj")

    def placements_for(layer_name, pname, p):
        base = [Replicate() for _ in mesh.dim_names]
        if tp_axis not in mesh.dim_names:
            return base
        ax = mesh.dim_names.index(tp_axis)
        leaf = layer_name.rsplit(".", 1)[-1]
        if leaf in col and pname == "weight":
            base[ax] = Shard(1)
        elif leaf in row and pname == "weight":
            base[ax] = Shard(0)
        elif leaf == "embed_tokens" and pname == "weight":
            base[ax] = Shard(1)
        return base

    def fn(name, sub, m):
        for pname, p in list(sub._parameters.items()):
            if p is None:
                continue
            sharded = shard_tensor(p, m, placements_for(name, pname, p))
            p._data = sharded._data
    return fn


# --- fused ops (registered so autograd tape + AMP see them) ---------------
from ..ops.registry import op as _op


@_op(name="llama_rope")
def rope_op(q, k, cos, sin):
    return apply_rotary_pos_emb(q, k, cos, sin)


@_op(name="flash_attention")
def flash_attention(q, k, v, attn_mask=None, is_causal=False):
    return sdpa(q, k, v, attn_mask=attn_mask, is_causal=is_causal)
