"""GPT-style decoder LM (learned positions, pre-LN, GELU MLP).

Reference analog: the GPT families the reference framework serves via
PaddleNLP, exercising paddle.nn.TransformerDecoder-style blocks and
fused attention (paddle/phi/kernels/fusion/fused_attention_kernel.cu).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .llama import flash_attention


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


class GPTAttention(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = config.head_dim
        self.qkv_proj = nn.Linear(h, 3 * h)
        self.out_proj = nn.Linear(h, h)

    def forward(self, x):
        b, s, h = x.shape
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        out = flash_attention(q, k, v, is_causal=True)
        return self.out_proj(out.reshape([b, s, h]))


class GPTBlock(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.ln_1 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.attn = GPTAttention(config)
        self.ln_2 = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)
        self.fc_in = nn.Linear(config.hidden_size, config.intermediate_size)
        self.fc_out = nn.Linear(config.intermediate_size, config.hidden_size)

    def forward(self, x):
        x = x + self.attn(self.ln_1(x))
        return x + self.fc_out(F.gelu(self.fc_in(self.ln_2(x))))


class GPTModel(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.config = config
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size)
        self.wpe = nn.Embedding(config.max_position_embeddings,
                                config.hidden_size)
        self.h = nn.LayerList([GPTBlock(config)
                               for _ in range(config.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size,
                                 epsilon=config.layer_norm_epsilon)

    def forward(self, input_ids):
        import paddle_tpu as P
        s = input_ids.shape[1]
        pos = P.arange(s, dtype="int64").unsqueeze(0)
        x = self.wte(input_ids) + self.wpe(pos)
        for blk in self.h:
            x = blk(x)
        return self.ln_f(x)


class GPTForCausalLM(nn.Layer):
    def __init__(self, config: GPTConfig):
        super().__init__()
        self.gpt = GPTModel(config)
        self.lm_head = nn.Linear(config.hidden_size, config.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids):
        return self.lm_head(self.gpt(input_ids))
