"""BERT encoder (reference analog: the BERT fine-tune rung of the
benchmark ladder, BASELINE.md #3; built on paddle.nn.TransformerEncoder
semantics — python/paddle/nn/layer/transformer.py).
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .llama import flash_attention


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    num_labels: int = 2
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


class BertEmbeddings(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.word_embeddings = nn.Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = nn.Embedding(c.max_position_embeddings,
                                                c.hidden_size)
        self.token_type_embeddings = nn.Embedding(c.type_vocab_size,
                                                  c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)

    def forward(self, input_ids, token_type_ids=None):
        import paddle_tpu as P
        s = input_ids.shape[1]
        pos = P.arange(s, dtype="int64").unsqueeze(0)
        e = self.word_embeddings(input_ids) + self.position_embeddings(pos)
        if token_type_ids is not None:
            e = e + self.token_type_embeddings(token_type_ids)
        return self.layer_norm(e)


class BertSelfAttention(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        h = c.hidden_size
        self.num_heads = c.num_attention_heads
        self.head_dim = c.head_dim
        self.query = nn.Linear(h, h)
        self.key = nn.Linear(h, h)
        self.value = nn.Linear(h, h)
        self.dense = nn.Linear(h, h)
        self.layer_norm = nn.LayerNorm(h, epsilon=c.layer_norm_eps)

    def forward(self, x, attn_mask=None):
        import paddle_tpu as paddle
        import paddle_tpu.nn.functional as F

        b, s, h = x.shape
        shp = [b, s, self.num_heads, self.head_dim]
        # fused QKV: one [h, 3h] matmul instead of three narrow [h, h]
        # ones (state-dict layout unchanged — q/k/v stay separate params;
        # the 3h-wide concat feeds the MXU ~30% better at hidden 768,
        # measured v5e)
        w = paddle.concat([self.query.weight, self.key.weight,
                           self.value.weight], axis=1)
        bias = paddle.concat([self.query.bias, self.key.bias,
                              self.value.bias], axis=0)
        qkv = F.linear(x, w, bias)
        q = qkv[:, :, :h].reshape(shp)
        k = qkv[:, :, h:2 * h].reshape(shp)
        v = qkv[:, :, 2 * h:].reshape(shp)
        out = flash_attention(q, k, v, attn_mask=attn_mask)
        out = self.dense(out.reshape([b, s, h]))
        return self.layer_norm(x + out)


class BertLayer(nn.Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.attention = BertSelfAttention(c)
        self.intermediate = nn.Linear(c.hidden_size, c.intermediate_size)
        self.output = nn.Linear(c.intermediate_size, c.hidden_size)
        self.layer_norm = nn.LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)

    def forward(self, x, attn_mask=None):
        x = self.attention(x, attn_mask=attn_mask)
        y = self.output(F.gelu(self.intermediate(x)))
        return self.layer_norm(x + y)


class BertModel(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        self.encoder = nn.LayerList(
            [BertLayer(config) for _ in range(config.num_hidden_layers)])
        self.pooler = nn.Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        x = self.embeddings(input_ids, token_type_ids)
        for layer in self.encoder:
            x = layer(x, attn_mask=attn_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class BertForSequenceClassification(nn.Layer):
    def __init__(self, config: BertConfig):
        super().__init__()
        self.bert = BertModel(config)
        self.classifier = nn.Linear(config.hidden_size, config.num_labels)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attn_mask)
        return self.classifier(pooled)
