"""Hybrid-parallel (pp × dp × tp + sp) Llama pretraining step, TPU-native.

Reference analog: the fleet hybrid-parallel stack —
fleet/meta_parallel/pipeline_parallel.py (1F1B :575, train_batch :820),
fleet/layers/mpu/mp_layers.py (Column/RowParallelLinear :336,:543),
fleet/utils/sequence_parallel_utils.py, hybrid_parallel_optimizer.py :266.

TPU formulation (SURVEY.md §7-§8): one jitted SPMD program over a
('pp','dp','tp') mesh.
  * tp  — GSPMD weight shardings (colwise Shard(-1) on q/k/v/gate/up,
          rowwise on o/down); XLA inserts the mp allreduces the reference
          codes by hand in mp_ops.py.
  * dp  — batch dim sharded; grad allreduce is XLA's psum, replacing the
          bucketed Reducer (fluid/distributed/collective/reducer.cc).
  * sp  — Megatron-SP: activations outside attention carry a
          sequence-dim sharding constraint over the tp axis, replacing the
          scatter/allgather PyLayers in sequence_parallel_utils.py.
  * pp  — stage-stacked weights sharded over 'pp'; activations hop
          stages via ppermute on ICI inside a shard_map that is manual
          over 'pp' only.  Two schedules: "gpipe" differentiates through
          the fill-drain scan (pipelining.py); "1f1b" (+ interleaved
          n_virtual>1) runs the hand-scheduled engine with bounded
          in-flight residuals (distributed/pipeline_schedules.py) —
          replacing pipeline_parallel.py:575/:1174 + p2p_communication.
  * remat — jax.checkpoint on the per-layer body (reference:
          fleet/recompute/recompute.py).

Everything below is pure functional jax: params/opt-state pytrees, one
donated train step.  This is the flagship path bench.py measures.
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import LlamaConfig, _rope_tables, apply_rotary_pos_emb
from ..distributed.pipeline_schedules import pipeline_1f1b
from ..ops.pallas.flash_attention import sdpa


# ----------------------------------------------------------------- mesh
def build_mesh(n_devices=None, pp=1, dp=1, tp=1, devices=None):
    """('pp','dp','tp') mesh. Axis sizes must multiply to n_devices."""
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    assert pp * dp * tp == n, (pp, dp, tp, n)
    grid = np.asarray(devices[:n]).reshape(pp, dp, tp)
    return Mesh(grid, ("pp", "dp", "tp"))


def default_axes(n):
    """Factorize n into the most BALANCED (pp, dp, tp) triple — every
    axis exercised when possible (8 -> 2x2x2, 64 -> 4x4x4, the v5p-64
    shape of BASELINE.json's north star)."""
    pp = max(d for d in range(1, int(round(n ** (1 / 3))) + 1)
             if n % d == 0)
    rem = n // pp
    tp = max(d for d in range(1, int(rem ** 0.5) + 1) if rem % d == 0)
    return pp, rem // tp, tp


# ------------------------------------------------------------ parameters
def init_params(config: LlamaConfig, n_pp: int, key, dtype=jnp.float32,
                n_virtual: int = 1):
    """Params pytree. Decoder leaves are stage-stacked:
    [n_pp, layers_per_stage, ...] (or [n_pp, n_virtual, lps, ...] for the
    interleaved schedule — device s owns virtual stages {c*n_pp+s})."""
    sv = n_pp * n_virtual
    assert config.num_hidden_layers % sv == 0
    lps = config.num_hidden_layers // sv
    lead = (n_pp, n_virtual, lps) if n_virtual > 1 else (n_pp, lps)
    h, i = config.hidden_size, config.intermediate_size
    hd, nh, kvh = config.head_dim, config.num_attention_heads, \
        config.num_key_value_heads
    ks = jax.random.split(key, 9)

    def w(k, *shape, fan_in):
        std = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, lead + shape, jnp.float32)
                * std).astype(dtype)

    layer = {
        "input_ln": jnp.ones(lead + (h,), dtype),
        "q": w(ks[0], h, nh * hd, fan_in=h),
        "k": w(ks[1], h, kvh * hd, fan_in=h),
        "v": w(ks[2], h, kvh * hd, fan_in=h),
        "o": w(ks[3], nh * hd, h, fan_in=nh * hd),
        "post_ln": jnp.ones(lead + (h,), dtype),
        "gate": w(ks[4], h, i, fan_in=h),
        "up": w(ks[5], h, i, fan_in=h),
        "down": w(ks[6], i, h, fan_in=i),
    }
    emb = (jax.random.normal(ks[7], (config.vocab_size, h), jnp.float32)
           * 0.02).astype(dtype)
    head = (jax.random.normal(ks[8], (h, config.vocab_size), jnp.float32)
            / math.sqrt(h)).astype(dtype)
    return {"embed": emb, "stages": layer,
            "norm": jnp.ones((h,), dtype), "head": head}


def param_shardings(mesh: Mesh, n_virtual: int = 1):
    """NamedShardings implementing the reference TP plan + pp stacking."""
    s = functools.partial(NamedSharding, mesh)
    pad = (None,) * (1 if n_virtual > 1 else 0)  # extra chunk dim
    col = s(P("pp", *pad, None, None, "tp"))  # [pp,(v),lps,in,out] colwise
    row = s(P("pp", *pad, None, "tp", None))  # row-parallel
    ln = s(P("pp", *pad, None, None))
    return {
        "embed": s(P(None, "tp")),
        "stages": {"input_ln": ln, "q": col, "k": col, "v": col, "o": row,
                   "post_ln": ln, "gate": col, "up": col, "down": row},
        "norm": s(P(None)),
        "head": s(P(None, "tp")),
    }


# ------------------------------------------------------------- layer math
def _rms(x, w, eps):
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax.lax.rsqrt(var + eps)).astype(x.dtype) * w


def _decoder_layer(lp, x, cos, sin, config: LlamaConfig):
    """One decoder layer, functional. x: [mb, S, H]."""
    nh, kvh, hd = (config.num_attention_heads, config.num_key_value_heads,
                   config.head_dim)
    b, sq, _ = x.shape
    r = x
    h = _rms(x, lp["input_ln"], config.rms_norm_eps)
    q = (h @ lp["q"]).reshape(b, sq, nh, hd)
    k = (h @ lp["k"]).reshape(b, sq, kvh, hd)
    v = (h @ lp["v"]).reshape(b, sq, kvh, hd)
    q, k = apply_rotary_pos_emb(q, k, cos, sin)
    a = sdpa(q, k, v, is_causal=True)
    from jax.ad_checkpoint import checkpoint_name as _ckpt_name
    a = _ckpt_name(a, "attn_out")
    x = r + (a.reshape(b, sq, nh * hd) @ lp["o"])
    r = x
    h = _rms(x, lp["post_ln"], config.rms_norm_eps)
    ff = jax.nn.silu(h @ lp["gate"]) * (h @ lp["up"])
    return r + ff @ lp["down"]


# Unroll the stage's layer loop instead of lax.scan.  The MoE-rung A/B
# measured ~2 ms/layer of scan stacked-weight overhead (BASELINE.md r5);
# default OFF here pending a same-session A/B on the 1B flagship (the
# scan is the known-good shipping config; flip via env to trial).
import os as _os

UNROLL_STAGE = _os.environ.get("PADDLE_TPU_UNROLL_STAGE", "0") == "1"


def _stage_fn(stage_params, x, cos, sin, config, remat=True):
    """Apply this stage's layers_per_stage layers (leaves [lps, ...]).
    remat: True = full per-layer checkpoint; "attn" = checkpoint but keep
    the flash-attention outputs resident (skips the most expensive
    recompute for ~1 GB at 1B/2k/8 scale); False = no remat."""
    body = functools.partial(_decoder_layer, cos=cos, sin=sin, config=config)
    if remat == "attn":
        body = jax.checkpoint(
            body, policy=jax.checkpoint_policies.save_only_these_names(
                "attn_out"))
    elif remat:
        body = jax.checkpoint(body)

    lps = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if UNROLL_STAGE and lps <= 32:
        h = x
        for i in range(lps):
            lp = jax.tree_util.tree_map(lambda a: a[i], stage_params)
            h = body(lp, h)
        return h

    def scan_body(h, lp):
        return body(lp, h), None
    out, _ = jax.lax.scan(scan_body, x, stage_params)
    return out


# --------------------------------------------------------------- pipeline
def pipelined_trunk(stacked, mbs, cos, sin, config, mesh, remat=True):
    """mbs: [M, mb, S, H] -> outputs of final stage, same shape.
    Manual over 'pp' only; dp/tp/sp stay under GSPMD inside."""
    n_pp = mesh.shape["pp"]
    if n_pp == 1:
        squeeze = jax.tree_util.tree_map(lambda a: a[0], stacked)
        return jax.vmap(
            lambda mb: _stage_fn(squeeze, mb, cos, sin, config, remat))(mbs)

    def per_device(stk, mbs):
        lp = jax.tree_util.tree_map(lambda a: a[0], stk)  # my stage
        stage = jax.lax.axis_index("pp")
        m = mbs.shape[0]
        total = m + n_pp - 1
        perm = [(i, (i + 1) % n_pp) for i in range(n_pp)]

        def tick(carry, t):
            state, outs = carry
            inj = mbs[jnp.minimum(t, m - 1)]
            state = jnp.where(stage == 0, inj, state)
            state = _stage_fn(lp, state, cos, sin, config, remat)
            oi = t - (n_pp - 1)
            ok = jnp.logical_and(stage == n_pp - 1,
                                 jnp.logical_and(oi >= 0, oi < m))
            idx = jnp.clip(oi, 0, m - 1)
            outs = outs.at[idx].set(jnp.where(ok, state, outs[idx]))
            state = jax.lax.ppermute(state, "pp", perm)
            return (state, outs), None

        init = (jnp.zeros_like(mbs[0]), jnp.zeros_like(mbs))
        (_, outs), _ = jax.lax.scan(tick, init, jnp.arange(total))
        # keep outs pp-stacked: only the last stage's row is real, and the
        # caller slices it — a broadcast from the last stage replaces the
        # old full-buffer psum (pp x less data on the wire)
        return outs[None]

    stacked_out = jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), stacked), P()),
        out_specs=P("pp"), axis_names=frozenset({"pp"}),
        check_vma=False)(stacked, mbs)
    return stacked_out[-1]


# ------------------------------------------------------------- train step
def loss_fn(params, ids, config: LlamaConfig, mesh: Mesh, n_micro=1,
            remat=True, sp=True):
    """Next-token CE over a [B, S+1] token batch."""
    inp, lab = ids[:, :-1], ids[:, 1:]
    b, s = inp.shape
    x = jnp.take(params["embed"], inp, axis=0)
    if mesh.shape["tp"] > 1:
        # the gather of a col-sharded [V, H/tp] table keeps tp on the
        # hidden dim; saying so stops GSPMD's "involuntary full
        # rematerialization" (replicate-then-reshard) of the embedding
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", None, "tp")))
    if sp and mesh.shape["tp"] > 1 and s % mesh.shape["tp"] == 0:
        # Megatron-SP: sequence dim sharded over tp outside attention
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P("dp", "tp", None)))
    cos, sin = _rope_tables(s, config.head_dim, config.rope_theta)
    mb = b // n_micro
    mbs = x.reshape(n_micro, mb, s, x.shape[-1])
    out = pipelined_trunk(params["stages"], mbs, cos, sin, config, mesh,
                          remat)
    h = out.reshape(b, s, -1)
    h = _rms(h, params["norm"], config.rms_norm_eps)
    return _chunked_ce_sum(h, lab, params["head"]) / (b * s)


def _chunked_ce_sum(h, lab, head):
    """Summed next-token CE.  For small [B,S,V] (≤ ~1.1 GB fp32) the
    logits fit HBM and ONE wide matmul beats the chunked path (the
    [tokens, V] head matmul is the fastest shape on the chip — measured
    ~8% of the MoE-rung step).  Above that, chunk over the sequence dim
    so the full fp32 logits never materialize (the usual OOM at vocab
    32k+); logsumexp's VJP re-derives softmax from the saved chunk logits
    instead of keeping a log_softmax copy."""
    b, s = lab.shape
    v = head.shape[-1]

    def ce_chunk(args):
        hc, lc = args
        logits = (hc @ head).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        tgt = jnp.take_along_axis(logits, lc[..., None], axis=-1)[..., 0]
        return jnp.sum(lse - tgt)

    if b * s * v * 4 <= 1.1e9:
        return ce_chunk((h.reshape(b * s, -1), lab.reshape(b * s)))

    n_chunks = next(c for c in (8, 7, 6, 5, 4, 3, 2, 1) if s % c == 0)
    hs = h.reshape(b, n_chunks, s // n_chunks, h.shape[-1]).swapaxes(0, 1)
    ls = lab.reshape(b, n_chunks, s // n_chunks).swapaxes(0, 1)
    return jnp.sum(jax.lax.map(jax.checkpoint(ce_chunk), (hs, ls)))


def grad_1f1b(params, ids, config: LlamaConfig, mesh: Mesh, n_micro,
              n_virtual=1, remat=True, sp=True, zero_bubble=False):
    """(loss, grads) via the hand-scheduled 1F1B / interleaved pipeline
    (distributed/pipeline_schedules.py) instead of AD through the GPipe
    scan.  Embedding runs at stage 0, final-norm+head+CE at the last
    stage, so each microbatch's backward starts as soon as its forward
    leaves the pipe — in-flight residuals are bounded by ~2*pp
    microbatches instead of all of them.

    Reference: fleet/meta_parallel/pipeline_parallel.py:575 (1F1B),
    :1174 (interleaved VPP)."""
    b, s_tot = ids.shape
    s = s_tot - 1
    assert b % n_micro == 0, (b, n_micro)
    aux = ids.reshape(n_micro, b // n_micro, s_tot)
    fp = {"embed": params["embed"]}
    lp = {"norm": params["norm"], "head": params["head"]}
    inv_tok = 1.0 / (b * s)
    cos, sin = _rope_tables(s, config.head_dim, config.rope_theta)

    def first_fn(fp, aux_j):
        # NOTE: unlike loss_fn, no explicit with_sharding_constraint here
        # — the XLA SPMD partitioner aborts on auto-axis constraints
        # inside this pp-manual shard_map (jaxlib 0.9 CPU, verified).
        # tp/dp placement of the gather follows GSPMD propagation from
        # the tp-sharded table instead; `sp` is honored by the gpipe
        # schedule only.
        return jnp.take(fp["embed"], aux_j[:, :-1], axis=0)

    def stage_fn(cp, x):
        return _stage_fn(cp, x, cos, sin, config, remat)

    def last_fn(lp, y, aux_j):
        h = _rms(y, lp["norm"], config.rms_norm_eps)
        return _chunked_ce_sum(h, aux_j[:, 1:], lp["head"]) * inv_tok

    stages = params["stages"]
    if n_virtual == 1:  # [pp, lps, ...] -> engine layout [pp, 1, lps, ...]
        stages = jax.tree_util.tree_map(lambda a: a[:, None], stages)
    loss, dstk, dfp, dlp = pipeline_1f1b(
        stage_fn, first_fn, last_fn, stages, fp, lp, aux, mesh,
        n_virtual=n_virtual, zero_bubble=zero_bubble)
    if n_virtual == 1:
        dstk = jax.tree_util.tree_map(lambda a: a[:, 0], dstk)
    grads = {"embed": dfp["embed"], "stages": dstk,
             "norm": dlp["norm"], "head": dlp["head"]}
    return loss, grads


class AdamWState(NamedTuple):
    step: jax.Array
    m: dict
    v: dict


def _f32_zeros_like(params):
    """fp32 buffers matching the param tree (optimizer state and grad
    accumulators share this dtype/shape contract)."""
    return jax.tree_util.tree_map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params)


def init_adamw(params):
    z = _f32_zeros_like(params)
    return AdamWState(jnp.zeros((), jnp.int32), z,
                      jax.tree_util.tree_map(jnp.copy, z))


def build_train_step(config: LlamaConfig, mesh: Mesh, lr=3e-4, wd=0.01,
                     n_micro=1, remat=True, sp=True, b1=0.9, b2=0.95,
                     eps=1e-8, grad_accum=1, schedule="gpipe",
                     n_virtual=1, zero1=False):
    """Returns jitted (params, opt, ids) -> (loss, params, opt).

    schedule: "gpipe" = AD through the fill-drain scan (pipelining.py);
    "1f1b" = hand-scheduled 1F1B (pipeline_schedules.py) with bounded
    in-flight residuals; "zb" = 1F1B with the ZB-H1 deferred-dW unit
    placement (zero_bubble=True, composes with VPP); n_virtual > 1
    selects the interleaved/VPP variant (params must come from
    setup(..., n_virtual=v)).

    grad_accum > 1 splits the batch into sequential chunks and averages
    their grads before ONE optimizer step (reference: gradient-merge
    pass / fleet accumulate_steps) — live activations stay bounded by
    one chunk, trading wall-clock for a larger effective batch."""
    use_1f1b = schedule in ("1f1b", "zb") and mesh.shape["pp"] > 1
    if n_virtual > 1 and not use_1f1b:
        raise ValueError(
            "n_virtual > 1 (interleaved/VPP) requires schedule='1f1b' "
            f"or 'zb' and a pp axis > 1; got schedule={schedule!r}, "
            f"pp={mesh.shape['pp']}")

    def one_batch(params, ids):
        if use_1f1b:
            return grad_1f1b(params, ids, config, mesh, n_micro,
                             n_virtual, remat, sp,
                             zero_bubble=schedule == "zb")
        return jax.value_and_grad(loss_fn)(
            params, ids, config, mesh, n_micro, remat, sp)

    def grad_of(params, ids):
        if grad_accum == 1:
            return one_batch(params, ids)
        b = ids.shape[0]
        assert b % grad_accum == 0, (b, grad_accum)
        chunks = ids.reshape(grad_accum, b // grad_accum, ids.shape[1])

        def acc(carry, chunk):
            lsum, gsum = carry
            loss, grads = one_batch(params, chunk)
            gsum = jax.tree_util.tree_map(
                lambda a, g: a + g.astype(jnp.float32), gsum, grads)
            return (lsum + loss, gsum), None

        (lsum, gsum), _ = jax.lax.scan(
            acc, (jnp.float32(0.0), _f32_zeros_like(params)), chunks)
        inv = 1.0 / grad_accum
        return lsum * inv, jax.tree_util.tree_map(
            lambda g: g * inv, gsum)

    def step(params, opt, ids):
        loss, grads = grad_of(params, ids)
        t = opt.step + 1
        tf = t.astype(jnp.float32)

        def upd(p, g, m, v, osh=None, psh=None):
            gf = g.astype(jnp.float32)
            m = b1 * m + (1 - b1) * gf
            v = b2 * v + (1 - b2) * jnp.square(gf)
            if osh is not None:
                # ZeRO-1: keep the fp32 state dp-sharded through the
                # update (each dp rank updates only its slice; GSPMD
                # shards the surrounding arithmetic to match)
                m = jax.lax.with_sharding_constraint(m, osh)
                v = jax.lax.with_sharding_constraint(v, osh)
            mhat = m / (1 - b1 ** tf)
            vhat = v / (1 - b2 ** tf)
            pf = p.astype(jnp.float32)
            pf = pf - lr * (mhat / (jnp.sqrt(vhat) + eps) + wd * pf)
            new_p = pf.astype(p.dtype)
            if psh is not None:
                # pin the updated param BACK to its own sharding: mixing
                # dp-sharded m/v into the update would otherwise let
                # GSPMD return dp-sharded params, violating the stage-1
                # contract (params stay replicated over dp) and forcing
                # a recompile + per-step all-gathers on the next call
                new_p = jax.lax.with_sharding_constraint(new_p, psh)
            return new_p, m, v

        flat_p, td = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(opt.m)
        flat_v = jax.tree_util.tree_leaves(opt.v)
        if zero1:
            flat_osh = jax.tree_util.tree_leaves(
                zero1_shardings(params, mesh, n_virtual))
            psh_tree = param_shardings(mesh, n_virtual)
            flat_psh = [
                NamedSharding(mesh, P(*(list(sh.spec)
                                        + [None] * (p.ndim
                                                    - len(sh.spec)))))
                for p, sh in zip(
                    flat_p, jax.tree_util.tree_leaves(psh_tree))]
        else:
            flat_osh = [None] * len(flat_p)
            flat_psh = [None] * len(flat_p)
        out = [upd(p, g, m, v, osh, psh) for p, g, m, v, osh, psh
               in zip(flat_p, flat_g, flat_m, flat_v, flat_osh, flat_psh)]
        new_p = jax.tree_util.tree_unflatten(td, [o[0] for o in out])
        new_m = jax.tree_util.tree_unflatten(td, [o[1] for o in out])
        new_v = jax.tree_util.tree_unflatten(td, [o[2] for o in out])
        return loss, new_p, AdamWState(t, new_m, new_v)

    return jax.jit(step, donate_argnums=(0, 1))


def zero1_shardings(params, mesh, n_virtual=1):
    """ZeRO-1 (sharding stage 1, reference fleet DygraphShardingOptimizer):
    optimizer-state shardings = the param sharding with the first
    dp-divisible unsharded axis re-sharded over 'dp', so each dp rank
    holds 1/dp of the fp32 m/v state.  Params/grads stay dp-replicated —
    GSPMD inserts the gather on read, which is exactly stage 1."""
    base = param_shardings(mesh, n_virtual)
    dp = mesh.shape["dp"]

    def one(p, sh):
        spec = list(sh.spec) + [None] * (p.ndim - len(sh.spec))
        if dp > 1:
            for ax in range(p.ndim):
                if spec[ax] is None and p.shape[ax] % dp == 0:
                    spec[ax] = "dp"
                    break
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(one, params, base)


def setup(config: LlamaConfig, mesh: Mesh, seed=0, dtype=jnp.float32,
          n_virtual=1, zero1=False):
    """Init + place params and optimizer state on the mesh.
    zero1=True places AdamW m/v dp-sharded (pair with
    build_train_step(zero1=True))."""
    params = init_params(config, mesh.shape["pp"], jax.random.key(seed),
                         dtype, n_virtual)
    sh = param_shardings(mesh, n_virtual)
    params = jax.tree_util.tree_map(jax.device_put, params, sh)
    opt = init_adamw(params)
    if zero1:
        osh = zero1_shardings(params, mesh, n_virtual)
        opt = AdamWState(
            opt.step,
            jax.tree_util.tree_map(jax.device_put, opt.m, osh),
            jax.tree_util.tree_map(jax.device_put, opt.v, osh))
    return params, opt
