"""Model zoo: flagship language models built on paddle_tpu.nn.

Reference analog: the in-tree Llama test model
(test/auto_parallel/hybrid_strategy/semi_auto_parallel_llama_model.py) plus
the PaddleNLP model families the reference framework exists to serve.
"""
from . import generation  # noqa: F401
from .generation import generate, GenerationConfig  # noqa: F401
from .llama import (  # noqa: F401
    LlamaConfig, LlamaRMSNorm, LlamaAttention, LlamaMLP, LlamaDecoderLayer,
    LlamaModel, LlamaForCausalLM, LlamaPretrainingCriterion,
    llama_tp_shard_fn)
from .gpt import GPTConfig, GPTModel, GPTForCausalLM  # noqa: F401
from .bert import BertConfig, BertModel, BertForSequenceClassification  # noqa: F401
