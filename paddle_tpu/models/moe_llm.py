"""Mixture-of-Experts decoder LM with expert-parallel training.

Reference analog: the MoE model stack the reference assembles from
incubate/distributed/models/moe/moe_layer.py (global_scatter/gather
all-to-all dispatch), the gating kernels (number_count/
limit_by_capacity/prune_gate_by_capacity, paddle/phi/kernels/gpu/), and
auto-parallel MoE (moe_global_mesh_tensor, spmd_rules/moe_gate_dispatch
.cc) — the DeepSeekMoE/Qwen2-MoE/Mixtral config family.

TPU formulation: one jitted SPMD program over a ('dp','ep') mesh —
tokens sharded over dp, expert-stacked weights Shard(0) over ep;
`distributed.moe.moe_dispatch_combine` expresses dispatch/combine as
einsums whose GSPMD lowering is the all-to-all pair the reference codes
by hand. Decoder layers run under one lax.scan (weights stacked [L,...])
with flash attention; the router's load-balancing aux loss accumulates
across layers.
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .llama import _rope_tables, apply_rotary_pos_emb
from .llama_hybrid import _rms, _chunked_ce_sum
from ..ops.pallas.flash_attention import sdpa
from ..distributed.moe import moe_dispatch_combine

__all__ = ["MoEConfig", "moe_tiny", "qwen2_moe_a14b", "deepseek_moe_16b",
           "init_params", "param_shardings", "build_mesh",
           "build_train_step", "setup"]


@dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 1024
    moe_intermediate_size: int = 1408
    num_hidden_layers: int = 8
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 8
    top_k: int = 2
    num_shared_experts: int = 0     # DeepSeekMoE: always-on dense experts
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    dtype: str = "float32"

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def moe_tiny(**kw) -> MoEConfig:
    cfg = dict(vocab_size=512, hidden_size=128, moe_intermediate_size=128,
               num_hidden_layers=2, num_attention_heads=4,
               num_key_value_heads=4, num_experts=4, top_k=2,
               max_position_embeddings=256)
    cfg.update(kw)
    return MoEConfig(**cfg)


def qwen2_moe_a14b() -> MoEConfig:
    """Qwen2-57B-A14B-shaped config (reference family).  Qwen2-MoE also
    carries a shared expert (shared_expert_intermediate_size) — modeled
    here as num_shared_experts * moe_intermediate_size."""
    return MoEConfig(
        vocab_size=151936, hidden_size=3584, moe_intermediate_size=2560,
        num_hidden_layers=28, num_attention_heads=28,
        num_key_value_heads=4, num_experts=64, top_k=8,
        num_shared_experts=8,
        max_position_embeddings=8192, dtype="bfloat16")


def deepseek_moe_16b() -> MoEConfig:
    """DeepSeekMoE-16B-shaped config: fine-grained routed experts plus
    2 shared experts that every token passes through (the DeepSeekMoE
    architecture; reference ships the family through its MoE layer +
    incubate/distributed/models/moe)."""
    return MoEConfig(
        vocab_size=102400, hidden_size=2048, moe_intermediate_size=1408,
        num_hidden_layers=28, num_attention_heads=16,
        num_key_value_heads=16, num_experts=64, top_k=6,
        num_shared_experts=2,
        max_position_embeddings=4096, dtype="bfloat16")


def build_mesh(n_devices=None, dp=1, ep=1, devices=None):
    devices = devices if devices is not None else jax.devices()
    n = n_devices or len(devices)
    assert dp * ep == n, (dp, ep, n)
    grid = np.asarray(devices[:n]).reshape(dp, ep)
    return Mesh(grid, ("dp", "ep"))


def init_params(config: MoEConfig, key, dtype=jnp.float32):
    L, h = config.num_hidden_layers, config.hidden_size
    f, E = config.moe_intermediate_size, config.num_experts
    hd, nh, kvh = (config.head_dim, config.num_attention_heads,
                   config.num_key_value_heads)
    ks = jax.random.split(key, 10)

    def w(k, *shape, fan_in):
        std = 1.0 / math.sqrt(fan_in)
        return (jax.random.normal(k, (L,) + shape, jnp.float32)
                * std).astype(dtype)

    return {
        "embed": (jax.random.normal(ks[0], (config.vocab_size, h),
                                    jnp.float32) * 0.02).astype(dtype),
        "layers": {
            "input_ln": jnp.ones((L, h), dtype),
            "q": w(ks[1], h, nh * hd, fan_in=h),
            "k": w(ks[2], h, kvh * hd, fan_in=h),
            "v": w(ks[3], h, kvh * hd, fan_in=h),
            "o": w(ks[4], nh * hd, h, fan_in=nh * hd),
            "post_ln": jnp.ones((L, h), dtype),
            "gate": w(ks[5], h, E, fan_in=h).astype(jnp.float32),
            "w1": w(ks[6], E, h, f, fan_in=h),
            "b1": jnp.zeros((L, E, f), dtype),
            "w2": w(ks[7], E, f, h, fan_in=f),
            "b2": jnp.zeros((L, E, h), dtype),
            **({"sw1": w(jax.random.fold_in(ks[9], 1), h,
                         config.num_shared_experts * f, fan_in=h),
                "sw2": w(jax.random.fold_in(ks[9], 2),
                         config.num_shared_experts * f, h,
                         fan_in=config.num_shared_experts * f)}
               if config.num_shared_experts else {}),
        },
        "norm": jnp.ones((h,), dtype),
        "head": (jax.random.normal(ks[8], (h, config.vocab_size),
                                   jnp.float32) / math.sqrt(h)).astype(
                                       dtype),
    }


def param_shardings(mesh: Mesh, config: MoEConfig | None = None,
                    params=None):
    """Sharding tree matching ``init_params``.  Pass the same ``config``
    (or the params tree itself) — presets with shared experts
    (qwen2_moe_a14b, deepseek_moe_16b) carry sw1/sw2 leaves that a
    config-less call cannot know about."""
    s = functools.partial(NamedSharding, mesh)
    rep2 = s(P(None, None))
    rep3 = s(P(None, None, None))
    exp = s(P(None, "ep", None, None))     # [L, E, ...] expert-sharded
    layers = {
        "input_ln": rep2, "q": rep3, "k": rep3, "v": rep3, "o": rep3,
        "post_ln": rep2, "gate": rep3,
        "w1": exp, "b1": s(P(None, "ep", None)), "w2": exp,
        "b2": s(P(None, "ep", None)),
    }
    shared = (config is not None and config.num_shared_experts) or \
        (params is not None and "sw1" in params.get("layers", {}))
    if shared:
        # shared experts run on EVERY token, so their weights shard the
        # inner (S*f) dim over ep, tensor-parallel style: GSPMD makes the
        # second matmul a partial-sum + allreduce and each chip stores
        # 1/ep of the biggest dense tensors in the model
        layers["sw1"] = s(P(None, None, "ep"))
        layers["sw2"] = s(P(None, "ep", None))
    return {
        "embed": rep2,
        "layers": layers,
        "norm": s(P(None)),
        "head": rep2,
    }


def _layer(lp, x, cos, sin, config: MoEConfig, mesh):
    nh, kvh, hd = (config.num_attention_heads, config.num_key_value_heads,
                   config.head_dim)
    b, sq, hdim = x.shape
    r = x
    h = _rms(x, lp["input_ln"], config.rms_norm_eps)
    # fused QKV projection: one [h, (nh+2kvh)*hd] matmul instead of three
    # narrow ones — wider N feeds the MXU better (measured ~18% faster on
    # v5e at hidden 1024); weights stay separate in the pytree, the
    # concat is 6MB and fuses away
    wqkv = jnp.concatenate([lp["q"], lp["k"], lp["v"]], axis=1)
    qkv = h @ wqkv
    q = qkv[..., :nh * hd].reshape(b, sq, nh, hd)
    k = qkv[..., nh * hd:(nh + kvh) * hd].reshape(b, sq, kvh, hd)
    v = qkv[..., (nh + kvh) * hd:].reshape(b, sq, kvh, hd)
    q, k = apply_rotary_pos_emb(q, k, cos, sin)
    a = sdpa(q, k, v, is_causal=True)
    x = r + (a.reshape(b, sq, nh * hd) @ lp["o"])
    r = x
    h = _rms(x, lp["post_ln"], config.rms_norm_eps)
    flat = h.reshape(b * sq, hdim)
    y, aux = moe_dispatch_combine(
        flat, lp["gate"], lp["w1"], lp["b1"], lp["w2"], lp["b2"],
        top_k=config.top_k, capacity_factor=config.capacity_factor,
        activation=jax.nn.silu, mesh=mesh, ep_axis="ep")
    if config.num_shared_experts:
        # DeepSeekMoE / Qwen2-MoE shared experts: a dense FFN every token
        # passes through, added to the routed output (no gating)
        y = y + jax.nn.silu(flat @ lp["sw1"]) @ lp["sw2"]
    return r + y.reshape(b, sq, hdim), aux


def loss_fn(params, ids, config: MoEConfig, mesh: Mesh):
    inp, lab = ids[:, :-1], ids[:, 1:]
    b, s = inp.shape
    x = jnp.take(params["embed"], inp, axis=0)
    x = jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("dp", None, None)))
    cos, sin = _rope_tables(s, config.head_dim, config.rope_theta)

    # UNROLLED layer loop for shallow stacks: lax.scan over stacked
    # weights cost ~2 ms/layer on v5e (stacked-xs slicing + dxs
    # accumulation in the backward) — the same-session A/B measured
    # 86.5 ms (scan) vs 71.0 ms (unrolled) for the 8-layer bench config.
    # Deep configs (Qwen2-MoE/DeepSeekMoE at 28 layers) keep the scan:
    # there the unrolled fwd+bwd HLO's compile time dominates.
    if config.num_hidden_layers <= 16:
        aux_total = jnp.float32(0.0)
        for i in range(config.num_hidden_layers):
            lp = jax.tree_util.tree_map(lambda a: a[i], params["layers"])
            x, a = _layer(lp, x, cos, sin, config, mesh)
            aux_total = aux_total + a
    else:
        def body(carry, lp):
            h, aux = carry
            h, a = _layer(lp, h, cos, sin, config, mesh)
            return (h, aux + a), None

        (x, aux_total), _ = jax.lax.scan(body, (x, jnp.float32(0.0)),
                                         params["layers"])
    h = _rms(x, params["norm"], config.rms_norm_eps)
    # chunked CE: never materialize the [B,S,V] fp32 logits
    ce = _chunked_ce_sum(h, lab, params["head"]) / (b * s)
    return ce + config.aux_loss_weight * aux_total / config.num_hidden_layers


def build_train_step(config: MoEConfig, mesh: Mesh, lr=3e-4):
    def step(params, ids):
        loss, grads = jax.value_and_grad(loss_fn)(params, ids, config,
                                                  mesh)
        params = jax.tree_util.tree_map(
            lambda p, g: (p.astype(jnp.float32)
                          - lr * g.astype(jnp.float32)).astype(p.dtype),
            params, grads)
        return loss, params

    return jax.jit(step, donate_argnums=(0,))


def setup(config: MoEConfig, mesh: Mesh, seed=0, dtype=None):
    if dtype is None:
        dtype = jnp.dtype(config.dtype)    # honor the config preset
    params = init_params(config, jax.random.key(seed), dtype)
    return jax.tree_util.tree_map(jax.device_put, params,
                                  param_shardings(mesh, config))
