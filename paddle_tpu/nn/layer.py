"""nn.Layer base class.

Reference: python/paddle/nn/layer/layers.py (Layer, ~2700 LoC) — parameter /
sublayer / buffer registries, hooks, state_dict, train/eval.  The TPU twist:
`functional_state` / `load_functional_state` expose all parameters+buffers as
a flat dict-of-jax-arrays pytree so a Layer can be run as a pure function
under jax.jit / pjit (see jit/functional.py).
"""
from __future__ import annotations

import collections
from typing import Iterator

import numpy as np

from ..framework.tensor import Tensor, to_tensor
from ..framework import dtype as dtypes

__all__ = ["Layer", "Parameter", "ParamAttr"]


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py Parameter)."""

    def __init__(self, data, dtype=None, stop_gradient=False, name=None):
        super().__init__(data, dtype=dtype, stop_gradient=stop_gradient,
                         name=name)
        self.persistable = True
        self.trainable = not stop_gradient
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


class ParamAttr:
    """Reference: python/paddle/base/param_attr.py."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None:
            return ParamAttr()
        if isinstance(arg, (list, tuple)):
            return [ParamAttr._to_attr(a) for a in arg]
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return False
        # an Initializer instance
        return ParamAttr(initializer=arg)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.dtype(dtype).name if dtype else "float32"
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._casted_by_pure_fp16 = False
        self._name_scope = name_scope or type(self).__name__.lower()

    # ------------------------------------------------------------ creation
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, XavierUniform
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtype or self._dtype
        from . import initializer as _init_mod
        glob = _init_mod._global_initializer
        glob_init = None
        if glob is not None:
            glob_init = glob[1] if is_bias else glob[0]
        init = attr.initializer or default_initializer or glob_init or \
            (Constant(0.0) if is_bias else XavierUniform())
        data = init(shape, dtype)
        p = Parameter(data, stop_gradient=not attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def add_parameter(self, name, parameter):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ------------------------------------------------------------ attr magic
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            buffers and buffers.pop(name, None)
            object.__setattr__(self, name, value)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            object.__setattr__(self, name, value)
        elif params is not None and name in params:
            params[name] = value
            object.__setattr__(self, name, value)
        elif buffers is not None and name in buffers:
            buffers[name] = value
            object.__setattr__(self, name, value)
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    # ------------------------------------------------------------ iteration
    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for lname, layer in self.named_sublayers(prefix=prefix,
                                                 include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{lname}.{pname}" if lname else pname), p

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters()]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from layer.named_sublayers(prefix=sub_prefix,
                                             include_self=True)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for _, l in self._sub_layers.items():
            if l is not None:
                yield l

    def named_children(self):
        for n, l in self._sub_layers.items():
            if l is not None:
                yield n, l

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for lname, layer in self.named_sublayers(prefix=prefix,
                                                 include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{lname}.{bname}" if lname else bname), b

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers()]

    # ------------------------------------------------------------- modes
    def train(self):
        self.training = True
        for l in self.children():
            l.train()
        return self

    def eval(self):
        self.training = False
        for l in self.children():
            l.eval()
        return self

    def apply(self, fn):
        for l in self.children():
            l.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------- forward
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        # Eager segment tracing (reference hot-path goal, phi/README.md
        # §1.2): a composite layer whose tree is hook/buffer-free runs
        # its WHOLE forward as one cached-jit dispatch — the dygraph
        # dispatch-count lever on a tunneled transport.  Purity is
        # enforced dynamically: the first dispatch doubles as a probe
        # (eager-RNG use or a trace failure falls back to per-op
        # forever).  Eligibility is per CLASS: framework-defined types
        # auto-segment, user subclasses opt in with
        # ``segment_forward = True`` (their forward may read mutable
        # Python state the probe cannot see).  See _segment_call and
        # layer_common.segment_eligible.
        if self._sub_layers and not self._forward_pre_hooks \
                and not self._forward_post_hooks:
            from . import layer_common as _lc
            if _lc.SEGMENT_FORWARD \
                    and _lc.segment_eligible(type(self)):
                out = self._segment_call(inputs, kwargs)
                if out is not NotImplemented:
                    return out
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    # --------------------------------------------- eager segment tracing
    def _segment_call(self, inputs, kwargs):
        """Run forward as ONE recorded op keyed on (structure
        fingerprint, input signature).  Returns NotImplemented when the
        segment path doesn't apply (traced input, AMP, hooks/buffers
        anywhere in the tree, unhashable statics, known-impure).

        Invalidation contract (tests/test_segment_forward.py): layer
        add/replace, hook registration, param REASSIGNMENT (the Tensor
        object changes — in-place optimizer updates do not), and
        train/eval flips all change the fingerprint and retrace.  Known
        limit (same as the reference's guard-free fast path): mutating a
        plain config attribute (e.g. a stored scale) after the first
        call is baked into the traced body.
        """
        import jax
        from jax.tree_util import tree_flatten, tree_unflatten

        from ..framework.tensor import Tensor
        from ..amp.auto_cast import _state as _amp_state
        from . import layer_common as _lc

        flat_in, treedef = tree_flatten(
            (inputs, kwargs), is_leaf=lambda t: isinstance(t, Tensor))
        t_set = {i for i, v in enumerate(flat_in)
                 if isinstance(v, Tensor)}
        if not t_set or _amp_state.enabled:
            return NotImplemented
        from ..ops import registry as _reg
        for i, v in enumerate(flat_in):
            if i in t_set:
                if isinstance(v._data, jax.core.Tracer):
                    return NotImplemented
            else:
                try:
                    _reg._static_fingerprint(v)
                except _reg._Unhashable:
                    return NotImplemented

        layers = list(self.sublayers(include_self=True))
        for l in layers:
            if l._buffers or l._forward_pre_hooks \
                    or l._forward_post_hooks:
                return NotImplemented
        fp = tuple(
            (type(l).__name__, id(l), l.training,
             tuple(id(p) for p in l._parameters.values()))
            for l in layers)
        # keyed by fingerprint so ALTERNATING structures (the classic
        # train()/eval() flip per epoch) reuse their traces instead of
        # minting a new segment name + full recompile per flip
        seg_map = self.__dict__.setdefault("_seg_cache_map", {})
        cached = seg_map.get(fp)
        if cached is None:
            if len(seg_map) >= 8:
                seg_map.pop(next(iter(seg_map)))
            # `layers` held strongly so fingerprinted ids can't be
            # recycled by a freed-and-replaced sublayer
            cached = (fp, True,
                      f"segment_{type(self).__name__}_"
                      f"{next(_lc._SEG_IDS)}",
                      list(self.parameters()), layers)
            seg_map[fp] = cached
        self.__dict__["_seg_cache"] = cached   # latest, for tests/debug
        _, pure, name, ps, _keep = cached
        if not pure:
            return NotImplemented

        n_in = len(flat_in)

        def body(*vals):
            from ..autograd import tape as _tape
            leaf_vals, pvals = vals[:n_in], vals[n_in:]
            saved = [p._data for p in ps]
            try:
                for p, v in zip(ps, pvals):
                    p._data = v
                flat2 = [Tensor(v, stop_gradient=True) if i in t_set
                         else v for i, v in enumerate(leaf_vals)]
                a2, k2 = tree_unflatten(treedef, flat2)
                with _tape.no_grad():
                    out = self.forward(*a2, **k2)
                out_flat, out_tree = tree_flatten(
                    out, is_leaf=lambda t: isinstance(t, Tensor))
                return tree_unflatten(
                    out_tree,
                    [t._data if isinstance(t, Tensor) else t
                     for t in out_flat])
            finally:
                for p, v in zip(ps, saved):
                    p._data = v

        try:
            out = _reg.apply_op(name, body, tuple(flat_in) + tuple(ps),
                                {})
        except Exception:
            # forward not traceable as one op (data-dependent python,
            # non-array outputs, ...): per-op path from now on
            impure = (fp, False, name, ps, layers)
            seg_map[fp] = impure
            self.__dict__["_seg_cache"] = impure
            return NotImplemented
        if name in _reg._UNCACHEABLE:
            # the probe saw eager RNG: this forward is not replayable
            # from a cached trace — mark impure (per-op from now on);
            # THIS call's output is already correct (fresh trace)
            impure = (fp, False, name, ps, layers)
            seg_map[fp] = impure
            self.__dict__["_seg_cache"] = impure
        return out

    def register_forward_pre_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # ------------------------------------------------------------ state
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            if name.split(".")[-1] not in self._non_persistable_buffer_names:
                dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing, unexpected = [], []
        for name, t in own.items():
            if name in state_dict:
                src = state_dict[name]
                arr = src.numpy() if hasattr(src, "numpy") else np.asarray(src)
                if list(arr.shape) != t.shape:
                    raise ValueError(
                        f"shape mismatch for {name}: {list(arr.shape)} vs {t.shape}")
                t.set_value(to_tensor(arr, dtype=t.dtype))
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    load_dict = set_state_dict
    set_dict = set_state_dict

    # ------------------------------------------------------------ dtype/device
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._to_dtype(dtypes.dtype(dtype))
        return self

    def astype(self, dtype):
        self._to_dtype(dtypes.dtype(dtype))
        return self

    def _to_dtype(self, dt):
        for _, p in self.named_parameters():
            if p.dtype.is_floating_point:
                p._data = p._data.astype(dt.np_dtype)
        for _, b in self.named_buffers():
            if b.dtype.is_floating_point:
                b._data = b._data.astype(dt.np_dtype)
        for l in self.sublayers(include_self=True):
            l._dtype = dt.name

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    # --------------------------------------------------- functional bridge
    def functional_state(self, trainable_only=False):
        """Flat {name: jax.Array} of parameters (+buffers unless
        trainable_only) — the pytree fed to jitted pure functions."""
        state = {}
        for name, p in self.named_parameters():
            if not trainable_only or p.trainable:
                state[name] = p._data
        if not trainable_only:
            for name, b in self.named_buffers():
                state["buffers." + name] = b._data
        return state

    def load_functional_state(self, state):
        """Point parameters/buffers at the given arrays (zero-copy rebind)."""
        params = dict(self.named_parameters())
        bufs = dict(self.named_buffers())
        for name, arr in state.items():
            if name.startswith("buffers."):
                bufs[name[len("buffers."):]]._data = arr
            else:
                params[name]._data = arr

    def clear_gradients(self, set_to_zero=True):
        for p in self.parameters():
            p.clear_grad(set_to_zero=False)

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self._sub_layers.items():
            child_repr = repr(child).split("\n")
            child_repr = "\n".join("  " + l for l in child_repr)
            lines.append(f"({name}): " + child_repr.lstrip())
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def extra_repr(self):
        return ""

    def full_name(self):
        return self._name_scope


class _HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self.id = _HookRemoveHelper._next_id
        _HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self.id, None)
