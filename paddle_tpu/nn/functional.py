"""nn.functional (reference: python/paddle/nn/functional/*; phi kernels
activation/conv/pool/norm/loss/...).  Each entry is a registered op: one
jax-pure body, one VJP, XLA fuses the elementwise chains into the matmuls.
Convolutions keep Paddle's NCHW/OIHW layout contract; XLA re-layouts for TPU
internally."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import op, apply_op
from ..framework.dtype import to_np_dtype
from ..framework import random as _random

# --------------------------------------------------------------- activations

@op
def relu(x, name=None):
    return jax.nn.relu(x)


@op
def relu6(x, name=None):
    return jnp.clip(x, 0, 6)


@op
def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=bool(approximate))


@op
def silu(x, name=None):
    return jax.nn.silu(x)


swish = silu


@op
def sigmoid(x, name=None):
    return jax.nn.sigmoid(x)


@op
def tanh(x, name=None):
    return jnp.tanh(x)


@op
def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(to_np_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


@op
def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(to_np_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


@op
def softmin(x, axis=-1, name=None):
    return jax.nn.softmax(-x, axis=axis)


@op
def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(x, negative_slope)


@op
def elu(x, alpha=1.0, name=None):
    return jax.nn.elu(x, alpha)


@op
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@op
def celu(x, alpha=1.0, name=None):
    return jax.nn.celu(x, alpha)


@op
def prelu(x, weight, data_format="NCHW", name=None):
    if weight.size == 1:
        w = weight.reshape(())
    else:
        c_axis = 1 if data_format == "NCHW" else x.ndim - 1
        shape = [1] * x.ndim
        shape[c_axis] = -1
        w = weight.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@op
def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=False, name=None):
    if training:
        a = jax.random.uniform(_random.split_key(), x.shape, jnp.float32,
                               lower, upper).astype(x.dtype)
    else:
        a = jnp.asarray((lower + upper) / 2, x.dtype)
    return jnp.where(x >= 0, x, a * x)


@op
def hardswish(x, name=None):
    return x * jnp.clip(x + 3, 0, 6) / 6


@op
def hardsigmoid(x, slope=1.0 / 6.0, offset=0.5, name=None):
    return jnp.clip(x * slope + offset, 0, 1)


@op
def hardtanh(x, min=-1.0, max=1.0, name=None):
    return jnp.clip(x, min, max)


@op
def hardshrink(x, threshold=0.5, name=None):
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros((), x.dtype))


@op
def softshrink(x, threshold=0.5, name=None):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold,
                               jnp.zeros((), x.dtype)))


@op
def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


@op
def softplus(x, beta=1.0, threshold=20.0, name=None):
    scaled = beta * x
    return jnp.where(scaled > threshold, x,
                     jnp.log1p(jnp.exp(scaled)) / beta)


@op
def softsign(x, name=None):
    return jax.nn.soft_sign(x)


@op
def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


@op
def glu(x, axis=-1, name=None):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@op
def maxout(x, groups, axis=1, name=None):
    c = x.shape[axis]
    shape = list(x.shape)
    shape[axis] = c // groups
    shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(shape), axis=axis + 1)


@op
def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = -jnp.log(-jnp.log(
        jax.random.uniform(_random.split_key(), x.shape, jnp.float32) + 1e-20)
        + 1e-20).astype(x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        y_hard = jax.nn.one_hot(jnp.argmax(y, axis=axis), x.shape[axis],
                                axis=axis, dtype=x.dtype)
        y = y_hard + y - jax.lax.stop_gradient(y)
    return y


# ------------------------------------------------------------------- linear

@op
def linear(x, weight, bias=None, name=None):
    # paddle weight layout: [in_features, out_features]
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@op(name="embedding")
def _embedding_dense(x, weight, padding_idx=None, name=None):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros((), out.dtype), out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Embedding lookup.  With ``sparse=True`` the weight gradient is
    recorded as a :class:`~paddle_tpu.framework.selected_rows.RowSparseGrad`
    (rows + value rows, never the dense [V, D] scatter) — the TPU analog of
    the reference's SelectedRows embedding grad
    (paddle/phi/kernels/selected_rows/, embedding_grad sparse branch in
    paddle/phi/ops/yaml/backward.yaml).  Consumed row-wise by SGD always
    and Adam/AdamW under ``lazy_mode=True``; other optimizers densify.
    """
    if sparse:
        from ..autograd import tape as _tape
        from ..framework.tensor import Tensor as _T
        w_is_tensor = isinstance(weight, _T)
        from ..static.graph import Variable as _V
        static = isinstance(x, _V) or isinstance(weight, _V)
        if (w_is_tensor and not static and _tape.is_grad_enabled()
                and not weight.stop_gradient):
            return _sparse_embedding_apply(x, weight, padding_idx)
    return _embedding_dense(x, weight, padding_idx=padding_idx)


_sparse_embedding_layer = None


def _sparse_embedding_apply(x, weight, padding_idx):
    global _sparse_embedding_layer
    from ..framework.tensor import Tensor

    if _sparse_embedding_layer is None:
        from ..autograd.py_layer import PyLayer
        from ..framework.selected_rows import RowSparseGrad

        class _SparseEmbedding(PyLayer):
            @staticmethod
            def forward(ctx, x_t, w_t, padding_idx):
                xi = x_t._data if isinstance(x_t, Tensor) \
                    else jnp.asarray(x_t)
                w = w_t._data
                ctx._xi, ctx._wshape, ctx._pad = xi, w.shape, padding_idx
                out = jnp.take(w, xi, axis=0)
                if padding_idx is not None:
                    out = jnp.where((xi == padding_idx)[..., None],
                                    jnp.zeros((), out.dtype), out)
                return Tensor(out, stop_gradient=False)

            @staticmethod
            def backward(ctx, dout):
                d = dout._data if isinstance(dout, Tensor) else dout
                xi = ctx._xi
                rows = xi.reshape(-1).astype(jnp.int32)
                vals = d.reshape((rows.shape[0],) + d.shape[xi.ndim:])
                if ctx._pad is not None:
                    vals = jnp.where((rows == ctx._pad)[:, None],
                                     jnp.zeros((), vals.dtype), vals)
                return None, RowSparseGrad(rows, vals, ctx._wshape)

        _sparse_embedding_layer = _SparseEmbedding

    x_t = x if isinstance(x, Tensor) else Tensor(jnp.asarray(x),
                                                 stop_gradient=True)
    return _sparse_embedding_layer.apply(x_t, weight, padding_idx)


@op
def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


@op
def bilinear(x1, x2, weight, bias=None, name=None):
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


# ------------------------------------------------------------------ dropout

@op
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    shape = list(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = [s if i in axes else 1 for i, s in enumerate(shape)]
    keep = jax.random.bernoulli(_random.split_key(), 1.0 - p, tuple(shape))
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


@op
def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    if not training or p == 0.0:
        return x
    c_axis = 1 if data_format == "NCHW" else 3
    shape = [x.shape[0], 1, 1, 1]
    shape[c_axis] = x.shape[c_axis]
    keep = jax.random.bernoulli(_random.split_key(), 1.0 - p, tuple(shape))
    return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))


@op
def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha_p = -1.7580993408473766
    keep = jax.random.bernoulli(_random.split_key(), 1.0 - p, x.shape)
    a = (1.0 / math.sqrt((alpha_p ** 2 * p + 1) * (1 - p)))
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, jnp.asarray(alpha_p, x.dtype)) + b


# -------------------------------------------------------------------- conv

def _pair(v, n=2):
    if isinstance(v, (list, tuple)):
        return tuple(int(i) for i in v)
    return (int(v),) * n


def _conv_padding(padding, nd):
    if isinstance(padding, str):
        return padding.upper()  # 'SAME' / 'VALID'
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * nd:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(nd)]
    return [tuple(p) for p in padding]


@op
def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    nd = 2
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCHW", "OIHW", "NCHW") if data_format == "NCHW"
        else ("NHWC", "OIHW", "NHWC"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_pair(stride, nd),
        padding=_conv_padding(padding, nd),
        rhs_dilation=_pair(dilation, nd), dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        shape = [1, -1, 1, 1] if data_format == "NCHW" else [1, 1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@op
def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape,
        ("NCH", "OIH", "NCH") if data_format == "NCL" else ("NHC", "OIH", "NHC"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_pair(stride, 1),
        padding=_conv_padding(padding, 1),
        rhs_dilation=_pair(dilation, 1), dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        shape = [1, -1, 1] if data_format == "NCL" else [1, 1, -1]
        out = out + bias.reshape(shape)
    return out


@op
def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    dn = jax.lax.conv_dimension_numbers(
        x.shape, weight.shape, ("NCDHW", "OIDHW", "NCDHW"))
    out = jax.lax.conv_general_dilated(
        x, weight, window_strides=_pair(stride, 3),
        padding=_conv_padding(padding, 3),
        rhs_dilation=_pair(dilation, 3), dimension_numbers=dn,
        feature_group_count=groups)
    if bias is not None:
        out = out + bias.reshape([1, -1, 1, 1, 1])
    return out


@op
def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    # paddle weight layout: [in, out/groups, kh, kw]; shared nd helper in
    # functional_extra (gradient-of-conv formulation)
    from .functional_extra import _conv_transpose_nd
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 2,
                              ("NCHW", "OIHW", "NCHW"), output_size)


# ------------------------------------------------------------------- pooling

def _ceil_pads(pads, spatial, k, s):
    """ceil_mode: extend the high-side padding so the last partial window
    is emitted (reference phi/kernels/funcs/pooling.h ceil output size;
    like the reference, a window that would start entirely inside the
    padding is NOT emitted).  Max pools pad with -inf and avg/lp pools
    pad with zeros + exclusive counts, so the extra region never
    distorts in-window values."""
    if isinstance(pads, str):
        return pads
    out = []
    for i, (lo, hi) in enumerate(pads):
        n_out = -(-(spatial[i] + lo + hi - k[i]) // s[i]) + 1  # ceil
        # drop trailing windows that start past the real input
        while n_out > 1 and (n_out - 1) * s[i] >= spatial[i] + lo:
            n_out -= 1
        extra = max(0, (n_out - 1) * s[i] + k[i] - (spatial[i] + lo + hi))
        out.append((lo, hi + extra))
    return out


@op
def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    k = _pair(kernel_size)
    s = _pair(stride if stride is not None else kernel_size)
    pads = _conv_padding(padding, 2)
    if ceil_mode:
        spatial = x.shape[2:4] if data_format == "NCHW" else x.shape[1:3]
        pads = _ceil_pads(pads, spatial, k, s)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_cfg = [(0, 0), (0, 0)] + (pads if not isinstance(pads, str) else pads)
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_cfg = [(0, 0)] + pads + [(0, 0)]
    if return_mask:
        from .functional_extra import _pool_argmax
        if data_format != "NCHW":  # pool spatial dims, not channels
            o, m = _pool_argmax(jnp.transpose(x, (0, 3, 1, 2)), k, s, pads)
            return (jnp.transpose(o, (0, 2, 3, 1)),
                    jnp.transpose(m, (0, 2, 3, 1)))
        return _pool_argmax(x, k, s, pads)
    neg = np.asarray(-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                     else np.iinfo(x.dtype).min, x.dtype)
    return jax.lax.reduce_window(x, neg, jax.lax.max, window, strides,
                                 pad_cfg)


@op
def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    k = _pair(kernel_size)
    s = _pair(stride if stride is not None else kernel_size)
    pads = _conv_padding(padding, 2)
    if ceil_mode:
        spatial = x.shape[2:4] if data_format == "NCHW" else x.shape[1:3]
        pads = _ceil_pads(pads, spatial, k, s)
    if data_format == "NCHW":
        window = (1, 1) + k
        strides = (1, 1) + s
        pad_cfg = [(0, 0), (0, 0)] + pads
    else:
        window = (1,) + k + (1,)
        strides = (1,) + s + (1,)
        pad_cfg = [(0, 0)] + pads + [(0, 0)]
    summed = jax.lax.reduce_window(x, np.zeros((), x.dtype), jax.lax.add,
                                   window, strides, pad_cfg)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        ones = jnp.ones_like(x)
        counts = jax.lax.reduce_window(ones, np.zeros((), x.dtype),
                                       jax.lax.add, window, strides, pad_cfg)
        return summed / counts
    return summed / (k[0] * k[1])


@op
def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    k = _pair(kernel_size, 1)
    s = _pair(stride if stride is not None else kernel_size, 1)
    pads = _conv_padding(padding, 1)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:3], k, s)
    if return_mask:
        from .functional_extra import _pool_argmax
        return _pool_argmax(x, k, s, pads)
    neg = np.asarray(-np.inf, x.dtype)
    return jax.lax.reduce_window(x, neg, jax.lax.max, (1, 1) + k, (1, 1) + s,
                                 [(0, 0), (0, 0)] + pads)


@op
def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    k = _pair(kernel_size, 1)
    s = _pair(stride if stride is not None else kernel_size, 1)
    pads = _conv_padding(padding, 1)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:3], k, s)
    summed = jax.lax.reduce_window(x, np.zeros((), x.dtype), jax.lax.add,
                                   (1, 1) + k, (1, 1) + s,
                                   [(0, 0), (0, 0)] + pads)
    ones = jnp.ones_like(x)
    counts = jax.lax.reduce_window(ones, np.zeros((), x.dtype), jax.lax.add,
                                   (1, 1) + k, (1, 1) + s,
                                   [(0, 0), (0, 0)] + pads)
    return summed / counts if exclusive else summed / k[0]


@op
def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    out_h, out_w = _pair(output_size)
    if data_format != "NCHW":
        x = jnp.transpose(x, (0, 3, 1, 2))
    n, c, h, w = x.shape
    if h % out_h == 0 and w % out_w == 0:
        out = x.reshape(n, c, out_h, h // out_h, out_w, w // out_w).mean((3, 5))
    else:
        # general: average over variable windows via cumulative sums
        def pool_axis(a, in_s, out_s, axis):
            starts = (np.arange(out_s) * in_s) // out_s
            ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
            pieces = [jnp.mean(jax.lax.slice_in_dim(a, int(st), int(en), axis=axis),
                               axis=axis, keepdims=True)
                      for st, en in zip(starts, ends)]
            return jnp.concatenate(pieces, axis=axis)
        out = pool_axis(pool_axis(x, h, out_h, 2), w, out_w, 3)
    if data_format != "NCHW":
        out = jnp.transpose(out, (0, 2, 3, 1))
    return out


@op
def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out_h, out_w = _pair(output_size)
    n, c, h, w = x.shape
    if h % out_h == 0 and w % out_w == 0:
        out = x.reshape(n, c, out_h, h // out_h, out_w, w // out_w).max((3, 5))
        return out
    def pool_axis(a, in_s, out_s, axis):
        starts = (np.arange(out_s) * in_s) // out_s
        ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
        pieces = [jnp.max(jax.lax.slice_in_dim(a, int(st), int(en), axis=axis),
                          axis=axis, keepdims=True)
                  for st, en in zip(starts, ends)]
        return jnp.concatenate(pieces, axis=axis)
    return pool_axis(pool_axis(x, h, out_h, 2), w, out_w, 3)


@op
def adaptive_avg_pool1d(x, output_size, name=None):
    n, c, l = x.shape
    out_l = int(output_size)
    if l % out_l == 0:
        return x.reshape(n, c, out_l, l // out_l).mean(-1)
    starts = (np.arange(out_l) * l) // out_l
    ends = ((np.arange(out_l) + 1) * l + out_l - 1) // out_l
    pieces = [jnp.mean(x[..., int(st):int(en)], axis=-1, keepdims=True)
              for st, en in zip(starts, ends)]
    return jnp.concatenate(pieces, axis=-1)


# ---------------------------------------------------------------- normalize

@op
def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)), x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


@op
def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """TPU-native fused rmsnorm (reference: paddle/phi/kernels/fusion
    fused_rms_norm); a Pallas variant lives in ops/pallas/rms_norm.py."""
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(var + epsilon)).astype(dt)
    if weight is not None:
        out = out * weight
    return out


@op
def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05,
               data_format="NCHW", use_global_stats=None, name=None):
    c_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = [1] * x.ndim
    bshape[c_axis] = -1

    use_batch_stats = training and not use_global_stats
    xf = x.astype(jnp.float32)  # fused into the reduce/elementwise loops
    if use_batch_stats:
        # One-pass sum + sum-of-squares stats in fp32 (E[x^2]-E[x]^2, the
        # same formulation as the reference's GPU kernel,
        # paddle/phi/kernels/gpu/batch_norm_kernel.cu): a single fused
        # read of x instead of the two-pass mean/var — measured ~10% of
        # the resnet50 train step on v5e.  Cancellation only degrades it
        # when |mean|/std >~ 1e3, far outside normal activation ranges.
        mean = jnp.mean(xf, axis=axes)
        sq = jnp.mean(jnp.square(xf), axis=axes)
        var = jnp.maximum(sq - jnp.square(mean), 0.0)  # guard fp rounding
        new_rm = (momentum * running_mean
                  + (1 - momentum) * mean).astype(running_mean.dtype)
        new_rv = (momentum * running_var
                  + (1 - momentum) * var).astype(running_var.dtype)
    else:
        mean = running_mean.astype(jnp.float32)
        var = running_var.astype(jnp.float32)
        new_rm, new_rv = running_mean, running_var

    out = (xf - mean.reshape(bshape)) * jax.lax.rsqrt(
        var.reshape(bshape) + epsilon)
    if weight is not None:
        out = out * weight.astype(jnp.float32).reshape(bshape)
    if bias is not None:
        out = out + bias.astype(jnp.float32).reshape(bshape)
    return out.astype(x.dtype), new_rm, new_rv


@op
def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-05,
               data_format="NCHW", name=None):
    if data_format != "NCHW":
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    g = num_groups
    grouped = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = jnp.mean(grouped, axis=axes, keepdims=True)
    var = jnp.var(grouped, axis=axes, keepdims=True)
    out = ((grouped - mean) * jax.lax.rsqrt(var + epsilon)).reshape(x.shape)
    bshape = [1, -1] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    if data_format != "NCHW":
        out = jnp.moveaxis(out, 1, -1)
    return out


@op
def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-05,
                  data_format="NCHW", name=None):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.var(x, axis=axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    bshape = [1, -1] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(bshape)
    if bias is not None:
        out = out + bias.reshape(bshape)
    return out


@op
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    pads = [(0, 0), (half, size - 1 - half), (0, 0), (0, 0)]
    padded = jnp.pad(sq, pads)
    acc = jax.lax.reduce_window(padded, np.zeros((), x.dtype), jax.lax.add,
                                (1, size, 1, 1), (1, 1, 1, 1),
                                [(0, 0)] * 4)
    return x / jnp.power(k + alpha * acc, beta)


@op
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    n = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True),
                  1.0 / p)
    return x / jnp.maximum(n, epsilon)


# ------------------------------------------------------------------- losses

def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@op
def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    n_classes = input.shape[axis]
    logp = jax.nn.log_softmax(input, axis=axis) if use_softmax \
        else jnp.log(jnp.clip(input, 1e-15, 1.0))
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis)
        mask = None
    else:
        lab = label
        if lab.ndim == input.ndim and lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis=axis)
        if label_smoothing > 0.0:
            onehot = jax.nn.one_hot(lab, n_classes, axis=axis, dtype=logp.dtype)
            smoothed = onehot * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(smoothed * logp, axis=axis)
        else:
            safe = jnp.where(lab == ignore_index, 0, lab)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis)
            loss = -jnp.squeeze(picked, axis=axis)
        mask = (lab != ignore_index)
        loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
        if weight is not None:
            w = jnp.take(weight, jnp.where(lab == ignore_index, 0, lab))
            w = jnp.where(mask, w, jnp.zeros((), w.dtype))
            loss = loss * w
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(w), 1e-12)
    if reduction == "mean" and not soft_label and mask is not None:
        denom = jnp.maximum(jnp.sum(mask.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / denom
    return _reduce(loss, reduction)


softmax_with_cross_entropy = cross_entropy


@op
def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    picked = jnp.take_along_axis(input, label[..., None].astype(jnp.int32),
                                 axis=-1 if input.ndim == 2 else 1)
    loss = -jnp.squeeze(picked, axis=-1 if input.ndim == 2 else 1)
    mask = label != ignore_index
    loss = jnp.where(mask, loss, jnp.zeros((), loss.dtype))
    if weight is not None:
        w = jnp.take(weight, jnp.where(mask, label, 0))
        loss = loss * jnp.where(mask, w, jnp.zeros((), w.dtype))
        if reduction == "mean":
            return jnp.sum(loss) / jnp.sum(jnp.where(mask, w, 0))
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(mask), 1)
    return _reduce(loss, reduction)


@op
def mse_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.square(input - label), reduction)


@op
def l1_loss(input, label, reduction="mean", name=None):
    return _reduce(jnp.abs(input - label), reduction)


@op
def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    d = jnp.abs(input - label)
    loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
    return _reduce(loss, reduction)


@op
def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.clip(input, eps, 1.0)) +
             (1 - label) * jnp.log(jnp.clip(1 - input, eps, 1.0)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


@op
def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    max_val = jnp.clip(-logit, 0, None)
    if pos_weight is not None:
        log_w = (pos_weight - 1) * label + 1
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


sigmoid_cross_entropy_with_logits = binary_cross_entropy_with_logits


@op
def kl_div(input, label, reduction="mean", log_target=False, name=None):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = jnp.where(label > 0, label * (jnp.log(jnp.clip(label, 1e-12, None))
                                             - input),
                         jnp.zeros((), input.dtype))
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


@op
def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    loss = jnp.clip(-label * (input - other) + margin, 0, None)
    return _reduce(loss, reduction)


@op
def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    loss = jnp.where(label == 1, input, jnp.clip(margin - input, 0, None))
    return _reduce(loss, reduction)


@op
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


@op
def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    cos = jnp.sum(input1 * input2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(input1, axis=-1) * jnp.linalg.norm(input2, axis=-1),
        1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
    return _reduce(loss, reduction)


@op
def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def dist(a, b):
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a - b) + epsilon, p),
                                 axis=-1), 1.0 / p)
    d_pos = dist(input, positive)
    d_neg = dist(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, dist(positive, negative))
    return _reduce(jnp.clip(d_pos - d_neg + margin, 0, None), reduction)


@op
def square_error_cost(input, label):
    return jnp.square(input - label)


@op
def log_loss(input, label, epsilon=1e-4, name=None):
    return -label * jnp.log(input + epsilon) \
        - (1 - label) * jnp.log(1 - input + epsilon)


# ------------------------------------------------------------- interpolate

@op
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    if data_format in ("NCHW", "NCW", "NCDHW"):
        spatial = x.shape[2:]
        chan_first = True
    else:
        spatial = x.shape[1:-1]
        chan_first = False
    if size is None:
        if not isinstance(scale_factor, (list, tuple)):
            scale_factor = [scale_factor] * len(spatial)
        size = [int(s * f) for s, f in zip(spatial, scale_factor)]
    else:
        if hasattr(size, "numpy"):
            size = size.numpy().tolist()
        size = [int(s) for s in (size if isinstance(size, (list, tuple)) else [size])]
    jmode = {"nearest": "nearest", "bilinear": "linear", "linear": "linear",
             "trilinear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if chan_first:
        out_shape = x.shape[:2] + tuple(size)
    else:
        out_shape = (x.shape[0],) + tuple(size) + (x.shape[-1],)
    if mode != "nearest" and align_corners:
        # jax.image.resize has no align_corners; emulate via scale_and_translate
        out = _resize_align_corners(x, out_shape, chan_first)
    else:
        out = jax.image.resize(x, out_shape, jmode)
    return out.astype(x.dtype)


def _resize_align_corners(x, out_shape, chan_first):
    sp_axes = list(range(2, x.ndim)) if chan_first else list(range(1, x.ndim - 1))
    out = x
    for ax in sp_axes:
        in_s, out_s = x.shape[ax], out_shape[ax]
        if in_s == out_s:
            continue
        idx = jnp.linspace(0.0, in_s - 1, out_s)
        lo = jnp.floor(idx).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, in_s - 1)
        w = (idx - lo).astype(x.dtype)
        shape = [1] * out.ndim
        shape[ax] = -1
        w = w.reshape(shape)
        out = jnp.take(out, lo, axis=ax) * (1 - w) + \
            jnp.take(out, hi, axis=ax) * w
    return out


upsample = interpolate


@op
def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c // (r * r), r, r, h, w)
    out = jnp.transpose(out, (0, 1, 4, 2, 5, 3))
    return out.reshape(n, c // (r * r), h * r, w * r)


@op
def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // r, r, w // r, r)
    out = jnp.transpose(out, (0, 1, 3, 5, 2, 4))
    return out.reshape(n, c * r * r, h // r, w // r)


@op
def unfold_(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    p = _conv_padding(paddings, 2)
    d = _pair(dilations)
    n, c, h, w = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, k, s, p, rhs_dilation=d,
        dimension_numbers=jax.lax.conv_dimension_numbers(
            x.shape, (1, c) + k, ("NCHW", "OIHW", "NCHW")),
        # the one-hot conv must not round through bf16 on the MXU:
        # unfold is a data movement op, values must come out bit-exact
        precision=jax.lax.Precision.HIGHEST)
    # [N, C*kh*kw, oh, ow] -> [N, C*kh*kw, L]
    return patches.reshape(n, patches.shape[1], -1)


# ------------------------------------------------------------- attention

@op
def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """Fused SDPA (reference: paddle fused attention / flash_attn kernels).
    Layout: [batch, seqlen, heads, head_dim] (paddle flash_attention layout).
    Dispatches to the Pallas flash kernel on TPU for long sequences."""
    from ..ops.pallas import flash_attention as _fa
    return _fa.sdpa(query, key, value, attn_mask=attn_mask,
                    dropout_p=dropout_p, is_causal=is_causal,
                    training=training)


@op
def softmax_mask_fuse_upper_triangle(x):
    n = x.shape[-1]
    mask = jnp.tril(jnp.ones((n, n), bool))
    masked = jnp.where(mask, x, jnp.asarray(-1e9, x.dtype))
    return jax.nn.softmax(masked, axis=-1)


# --------------------------------------------------------------------- misc

@op
def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


@op
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    batch = anchor.shape[0]
    sim = jnp.matmul(anchor, positive.T)
    lab = labels.reshape(-1, 1)
    target = (lab == lab.T).astype(sim.dtype)
    target = target / jnp.sum(target, axis=1, keepdims=True)
    ce = jnp.mean(jnp.sum(-target * jax.nn.log_softmax(sim, axis=1), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(jnp.square(anchor), 1)) +
                    jnp.mean(jnp.sum(jnp.square(positive), 1))) / 2
    return ce + reg


@op
def pad_sequence(sequences, padding_value=0.0, batch_first=False):
    max_len = int(np.max([s.shape[0] for s in sequences]))
    padded = [jnp.pad(s, [(0, max_len - s.shape[0])] + [(0, 0)] * (s.ndim - 1),
                      constant_values=padding_value) for s in sequences]
    out = jnp.stack(padded, axis=0)
    return out if batch_first else jnp.swapaxes(out, 0, 1)


@op
def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    nt, c, h, w = x.shape
    n = nt // seg_num
    x5 = x.reshape(n, seg_num, c, h, w)
    fold = int(c * shift_ratio)
    left = jnp.concatenate([x5[:, 1:, :fold], jnp.zeros_like(x5[:, :1, :fold])], 1)
    right = jnp.concatenate([jnp.zeros_like(x5[:, :1, fold:2 * fold]),
                             x5[:, :-1, fold:2 * fold]], 1)
    rest = x5[:, :, 2 * fold:]
    return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """Inverse of unfold_: scatter-add [B, C*kh*kw, L] patches back to
    [B, C, H, W] (reference: python/paddle/nn/functional/common.py fold,
    phi fold kernels)."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)

    def body(xarr):
        b, ckk, L = xarr.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        assert nh * nw == L, (nh, nw, L)
        patches = xarr.reshape(b, c, kh, kw, nh, nw)
        out = jnp.zeros((b, c, oh + 2 * ph, ow + 2 * pw), xarr.dtype)
        # scatter-add each kernel offset's strided grid in one slice-add
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi:hi + nh * sh:sh,
                             wj:wj + nw * sw:sw].add(patches[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]

    return apply_op("fold", body, (x,), {})


# surface part 2 (3d pools, unpool, transposed convs, ctc/rnnt/... losses)
from .functional_extra import *  # noqa: E402,F401,F403
from .functional_extra2 import *  # noqa: E402,F401,F403

# paddle-shaped aliases / in-place functional forms
from ..ops.manipulation import pad  # noqa: E402,F401
unfold = unfold_  # noqa: E402  (im2col; `unfold_` kept for back-compat)


def _make_functional_inplace(fn):
    def inplace(x, *args, **kwargs):
        out = fn(x, *args, **kwargs)
        return x._rebind_(out)
    inplace.__name__ = fn.__name__ + "_"
    return inplace


relu_ = _make_functional_inplace(relu)
elu_ = _make_functional_inplace(elu)
tanh_ = _make_functional_inplace(tanh)
softmax_ = _make_functional_inplace(softmax)
leaky_relu_ = _make_functional_inplace(leaky_relu)
hardtanh_ = _make_functional_inplace(hardtanh)
from .functional_extra import thresholded_relu as _thr  # noqa: E402
thresholded_relu_ = _make_functional_inplace(_thr)
