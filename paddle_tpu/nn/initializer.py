"""Weight initializers (reference: python/paddle/nn/initializer/*).

Each initializer is a callable (shape, dtype) -> jax array, drawing keys
from the global generator so `paddle.seed` reproduces init.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import random as _random
from ..framework.dtype import to_np_dtype

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Dirac", "Orthogonal", "Bilinear", "calculate_gain",
           "set_global_initializer"]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) < 1:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle Linear weights are [in, out]; conv weights [out, in, kh, kw]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_out, fan_in = shape[0] * receptive, shape[1] * receptive
    return fan_in, fan_out


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param or 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    return gains[nonlinearity]


class Initializer:
    def __call__(self, shape, dtype="float32"):
        raise NotImplementedError

    def _key(self):
        return _random.split_key()


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        return jnp.full(tuple(shape), self.value, to_np_dtype(dtype))


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype="float32"):
        dt = to_np_dtype(dtype)
        sample_dt = jnp.float32 if dt == np.dtype("bfloat16") or \
            np.issubdtype(dt, np.floating) and dt.itemsize < 4 else dt
        z = jax.random.normal(self._key(), tuple(shape), jnp.float32)
        return (z * self.std + self.mean).astype(dt)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype="float32"):
        dt = to_np_dtype(dtype)
        lo = (self.a - 0.0)
        z = jax.random.truncated_normal(self._key(), self.a, self.b,
                                        tuple(shape), jnp.float32)
        return (z * self.std + self.mean).astype(dt)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def __call__(self, shape, dtype="float32"):
        dt = to_np_dtype(dtype)
        u = jax.random.uniform(self._key(), tuple(shape), jnp.float32,
                               self.low, self.high)
        return u.astype(dt)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        z = jax.random.normal(self._key(), tuple(shape), jnp.float32)
        return (z * std).astype(to_np_dtype(dtype))


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype="float32"):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        u = jax.random.uniform(self._key(), tuple(shape), jnp.float32,
                               -limit, limit)
        return u.astype(to_np_dtype(dtype))


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        z = jax.random.normal(self._key(), tuple(shape), jnp.float32)
        return (z * std).astype(to_np_dtype(dtype))


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu",
                 name=None):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype="float32"):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        u = jax.random.uniform(self._key(), tuple(shape), jnp.float32,
                               -limit, limit)
        return u.astype(to_np_dtype(dtype))


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def __call__(self, shape, dtype="float32"):
        arr = self.value.numpy() if hasattr(self.value, "numpy") \
            else np.asarray(self.value)
        return jnp.asarray(arr, to_np_dtype(dtype)).reshape(tuple(shape))


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def __call__(self, shape, dtype="float32"):
        out = np.zeros(tuple(shape), to_np_dtype(dtype))
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        for i in range(min(oc, ic * self.groups)):
            out[(i, i % ic) + mid] = 1
        return jnp.asarray(out)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def __call__(self, shape, dtype="float32"):
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        z = jax.random.normal(self._key(), (max(rows, cols), min(rows, cols)),
                              jnp.float32)
        q, r = jnp.linalg.qr(z)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(tuple(shape)).astype(
            to_np_dtype(dtype))


class Bilinear(Initializer):
    """Bilinear upsampling kernel init for transposed convs (reference
    nn/initializer/Bilinear)."""

    def __call__(self, shape, dtype=None):
        shape = tuple(int(s) for s in shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer expects a 4-D shape")
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        k = np.zeros(shape, np.float32)
        for i in range(int(np.prod(shape[2:]))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            k[:, :, y, x] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        return jnp.asarray(k).astype(to_np_dtype(dtype or "float32"))


_global_initializer = None


def set_global_initializer(weight_init, bias_init=None):
    """Default initializer override (reference nn/initializer/
    set_global_initializer): picked up by Layer.create_parameter."""
    global _global_initializer
    _global_initializer = (weight_init, bias_init)
