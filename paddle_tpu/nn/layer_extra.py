"""Layer surface part 2 — classes completing parity with
python/paddle/nn/layer/{pooling,conv,loss,activation,common}.py."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from .layer import Layer
from .initializer import Uniform
from . import functional as F

__all__ = [
    "MaxPool3D", "AvgPool3D", "AdaptiveAvgPool3D", "AdaptiveMaxPool1D",
    "AdaptiveMaxPool3D", "LPPool1D", "LPPool2D", "FractionalMaxPool2D",
    "FractionalMaxPool3D", "MaxUnPool1D", "MaxUnPool2D", "MaxUnPool3D",
    "Conv1DTranspose", "Conv3DTranspose", "Dropout3D", "FeatureAlphaDropout",
    "LogSigmoid", "ThresholdedReLU", "Unflatten", "ZeroPad1D", "ZeroPad3D",
    "GaussianNLLLoss", "PoissonNLLLoss", "MultiMarginLoss",
    "MultiLabelSoftMarginLoss", "SoftMarginLoss",
    "TripletMarginWithDistanceLoss", "CTCLoss", "RNNTLoss", "HSigmoidLoss",
    "AdaptiveLogSoftmaxWithLoss", "ParameterDict",
]


# ------------------------------------------------------------------ pooling

class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        k, s, p, cm, rm, df = self.args
        return F.max_pool3d(x, k, stride=s, padding=p, ceil_mode=cm,
                            return_mask=rm, data_format=df)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        k, s, p, cm, ex, dv, df = self.args
        return F.avg_pool3d(x, k, stride=s, padding=p, ceil_mode=cm,
                            exclusive=ex, divisor_override=dv, data_format=df)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size,
                                     return_mask=self.return_mask)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        nt, k, s, p, cm, df = self.args
        return F.lp_pool1d(x, nt, k, stride=s, padding=p, ceil_mode=cm,
                           data_format=df)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (norm_type, kernel_size, stride, padding, ceil_mode,
                     data_format)

    def forward(self, x):
        nt, k, s, p, cm, df = self.args
        return F.lp_pool2d(x, nt, k, stride=s, padding=p, ceil_mode=cm,
                           data_format=df)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, rm = self.args
        return F.fractional_max_pool2d(x, o, kernel_size=k, random_u=u,
                                       return_mask=rm)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        o, k, u, rm = self.args
        return F.fractional_max_pool3d(x, o, kernel_size=k, random_u=u,
                                       return_mask=rm)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, o = self.args
        return F.max_unpool1d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=o)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, o = self.args
        return F.max_unpool2d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=o)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, data_format, output_size)

    def forward(self, x, indices):
        k, s, p, df, o = self.args
        return F.max_unpool3d(x, indices, k, stride=s, padding=p,
                              data_format=df, output_size=o)


# ---------------------------------------------------------- transposed conv

class _ConvTransposeNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, nd, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        from .functional import _pair
        k = _pair(kernel_size, nd)
        fan_in = in_channels * int(np.prod(k))
        bound = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + k, attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (out_channels,), attr=bias_attr, is_bias=True,
                default_initializer=Uniform(-bound, bound))
        self.args = (stride, padding, output_padding, groups, dilation,
                     data_format)


class Conv1DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, output_padding, groups, dilation,
                         weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        s, p, op_, g, d, df = self.args
        return F.conv1d_transpose(x, self.weight, self.bias, stride=s,
                                  padding=p, output_padding=op_, groups=g,
                                  dilation=d, output_size=output_size,
                                  data_format=df)


class Conv3DTranspose(_ConvTransposeNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, output_padding, groups, dilation,
                         weight_attr, bias_attr, data_format)

    def forward(self, x, output_size=None):
        s, p, op_, g, d, df = self.args
        return F.conv3d_transpose(x, self.weight, self.bias, stride=s,
                                  padding=p, output_padding=op_, groups=g,
                                  dilation=d, output_size=output_size,
                                  data_format=df)


# ------------------------------------------------------------ small layers

class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, p=self.p, training=self.training)


class LogSigmoid(Layer):
    def forward(self, x):
        return F.log_sigmoid(x)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, value=0.0, name=None):
        super().__init__()
        self.threshold = threshold
        self.value = value

    def forward(self, x):
        return F.thresholded_relu(x, self.threshold, self.value)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        return F.unflatten(x, self.axis, self.shape)


class ZeroPad1D(Layer):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__()
        from .layer_common import Pad1D
        self._pad = Pad1D(padding, mode="constant", value=0.0,
                          data_format=data_format)

    def forward(self, x):
        return self._pad(x)


class ZeroPad3D(Layer):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__()
        from .layer_common import Pad3D
        self._pad = Pad3D(padding, mode="constant", value=0.0,
                          data_format=data_format)

    def forward(self, x):
        return self._pad(x)


# -------------------------------------------------------------------- losses

class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full, self.epsilon, self.reduction = full, epsilon, reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, full=self.full,
                                   epsilon=self.epsilon,
                                   reduction=self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        li, fu, ep, red = self.args
        return F.poisson_nll_loss(input, label, log_input=li, full=fu,
                                  epsilon=ep, reduction=red)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, red = self.args
        return F.multi_margin_loss(input, label, p=p, margin=m, weight=w,
                                   reduction=red)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight, self.reduction = weight, reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(
            input, label, weight=self.weight, reduction=self.reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        df, m, sw, red = self.args
        return F.triplet_margin_with_distance_loss(
            input, positive, negative, distance_function=df, margin=m,
            swap=sw, reduction=red)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean", name=None):
        super().__init__()
        self.blank, self.reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.blank = blank
        self.fastemit_lambda = fastemit_lambda
        self.reduction = reduction

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           blank=self.blank,
                           fastemit_lambda=self.fastemit_lambda,
                           reduction=self.reduction)


class HSigmoidLoss(Layer):
    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        if num_classes < 2:
            raise ValueError("num_classes must be >= 2")
        self.num_classes = num_classes
        self.is_custom = is_custom
        bound = 1.0 / math.sqrt(feature_size)
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr,
            default_initializer=Uniform(-bound, bound))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                (num_classes - 1, 1), attr=bias_attr, is_bias=True,
                default_initializer=Uniform(-bound, bound))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """Efficient softmax approximation (reference
    python/paddle/nn/layer/loss.py AdaptiveLogSoftmaxWithLoss): frequent
    classes in a head cluster, rare classes in down-projected tail clusters."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = list(cutoffs)
        if not cutoffs or cutoffs != sorted(set(cutoffs)) \
                or cutoffs[-1] > n_classes - 1 or min(cutoffs) <= 0:
            raise ValueError(
                "cutoffs should be a sorted list of unique positive ints "
                "< n_classes-1")
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs + [n_classes]
        self.div_value = div_value
        self.n_clusters = len(self.cutoffs) - 1
        self.head_size = self.cutoffs[0] + self.n_clusters
        bound = 1.0 / math.sqrt(in_features)
        self.head_weight = self.create_parameter(
            (in_features, self.head_size),
            default_initializer=Uniform(-bound, bound))
        self.head_bias = self.create_parameter(
            (self.head_size,), is_bias=True,
            default_initializer=Uniform(-bound, bound)) \
            if head_bias else None
        self._tail_w1 = []
        self._tail_w2 = []
        for i in range(self.n_clusters):
            hsz = max(int(in_features // (div_value ** (i + 1))), 1)
            osz = self.cutoffs[i + 1] - self.cutoffs[i]
            w1 = self.create_parameter(
                (in_features, hsz),
                default_initializer=Uniform(-bound, bound))
            w2 = self.create_parameter(
                (hsz, osz),
                default_initializer=Uniform(-bound, bound))
            self.add_parameter(f"tail_w1_{i}", w1)
            self.add_parameter(f"tail_w2_{i}", w2)
            self._tail_w1.append(w1)
            self._tail_w2.append(w2)

    def _head_log_prob(self, input):
        head = F.linear(input, self.head_weight, self.head_bias)
        return F.log_softmax(head, axis=-1)

    def forward(self, input, label):
        head_lp = self._head_log_prob(input)          # [N, head_size]
        shortlist = self.cutoffs[0]
        lab = label.astype("int32")
        # head (frequent) classes: gather at min(label, shortlist-1); masked
        in_head = (lab < shortlist).astype(head_lp.dtype)
        safe_head = lab.clip(0, shortlist - 1)
        head_take = head_lp.take_along_axis(
            safe_head.reshape((-1, 1)), 1).reshape((-1,))
        out = head_take * in_head
        # tail clusters: log p = head log p of cluster + in-cluster log p
        for i in range(self.n_clusters):
            lo, hi = self.cutoffs[i], self.cutoffs[i + 1]
            mask = ((lab >= lo).astype(head_lp.dtype)
                    * (lab < hi).astype(head_lp.dtype))
            rel = (lab - lo).clip(0, hi - lo - 1)
            h = input.matmul(self._tail_w1[i]).matmul(self._tail_w2[i])
            tail_lp = F.log_softmax(h, axis=-1)
            cluster_lp = head_lp[:, shortlist + i]
            take = tail_lp.take_along_axis(
                rel.reshape((-1, 1)), 1).reshape((-1,))
            out = out + (cluster_lp + take) * mask
        loss = -(out.mean())
        return out, loss

    def log_prob(self, input):
        import paddle_tpu
        head_lp = self._head_log_prob(input)
        shortlist = self.cutoffs[0]
        pieces = [head_lp[:, :shortlist]]
        for i in range(self.n_clusters):
            h = input.matmul(self._tail_w1[i]).matmul(self._tail_w2[i])
            tail_lp = F.log_softmax(h, axis=-1)
            pieces.append(tail_lp + head_lp[:, shortlist + i].reshape((-1, 1)))
        return paddle_tpu.concat(pieces, axis=1)

    def predict(self, input):
        return self.log_prob(input).argmax(axis=-1)


class ParameterDict(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, v in items:
            self.add_parameter(str(k), v)
        return self

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, value):
        self.add_parameter(str(key), value)

    def __contains__(self, key):
        return key in self._parameters

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()
