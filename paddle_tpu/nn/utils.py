"""paddle.nn.utils (reference: python/paddle/nn/utils/{weight_norm_hook,
spectral_norm_hook,clip_grad_norm_,clip_grad_value_,transform_parameters}.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def _norm_except_dim(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w), axis=axes, keepdims=True))


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize ``layer.<name>`` as g * v/||v|| (reference
    weight_norm_hook.py): adds <name>_g and <name>_v parameters and a
    pre-forward hook recomputing the weight."""
    from .layer import Parameter
    w = getattr(layer, name)
    if dim is None:
        dim = -1  # norm over everything
    data = w._data
    if dim == -1:
        g = jnp.sqrt(jnp.sum(jnp.square(data))).reshape(1)
    else:
        g = _norm_except_dim(data, dim).reshape(-1)
    g_p = Parameter(g)
    v_p = Parameter(data)
    layer.add_parameter(name + "_g", g_p)
    layer.add_parameter(name + "_v", v_p)
    if name in layer._parameters:
        del layer._parameters[name]
    # recompute through the tape on every forward so grads flow to v and g
    hook = layer.register_forward_pre_hook(
        lambda lyr, inputs: _apply_weight_norm(lyr, name, dim))
    layer._weight_norm_hook = hook
    layer._weight_norm_dim = dim
    _apply_weight_norm(layer, name, dim)
    return layer


def _apply_weight_norm(layer, name, dim):
    import paddle_tpu as P
    v = getattr(layer, name + "_v")
    g = getattr(layer, name + "_g")
    nd = v._data.ndim
    if dim == -1:
        t = v * (g / P.sqrt(P.sum(v * v)))
    else:
        shape = [1] * nd
        shape[dim] = -1
        t = v * (g.reshape(shape) / P.sqrt(
            P.sum(v * v, axis=[i for i in range(nd) if i != dim],
                  keepdim=True)))
    object.__setattr__(layer, name, t)
    return None


def remove_weight_norm(layer, name="weight"):
    """(reference weight_norm_hook.py remove_weight_norm)"""
    from .layer import Parameter
    _apply_weight_norm(layer, name,
                       getattr(layer, "_weight_norm_dim", 0))
    w = getattr(layer, name)
    p = Parameter(w._data)
    del layer._parameters[name + "_g"]
    del layer._parameters[name + "_v"]
    if getattr(layer, "_weight_norm_hook", None) is not None:
        layer._weight_norm_hook.remove()
        layer._weight_norm_hook = None
    layer.add_parameter(name, p)
    setattr(layer, name, p)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Spectral normalization via power iteration (reference
    spectral_norm_hook.py): weight / sigma_max, u/v persisted as buffers."""
    from ..framework import random as _random
    import jax
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(w._data, dim, 0).reshape(w._data.shape[dim], -1)
    h, ww = mat.shape
    u0 = jax.random.normal(_random.split_key(), (h,))
    v0 = jax.random.normal(_random.split_key(), (ww,))
    layer.register_buffer(name + "_u", Tensor(u0 / jnp.linalg.norm(u0)))
    layer.register_buffer(name + "_v", Tensor(v0 / jnp.linalg.norm(v0)))
    from .layer import Parameter
    orig = Parameter(w._data)
    layer.add_parameter(name + "_orig", orig)
    if name in layer._parameters:
        del layer._parameters[name]

    def pre_hook(lyr, inputs):
        wd = orig._data
        m = jnp.moveaxis(wd, dim, 0).reshape(wd.shape[dim], -1)
        u = getattr(lyr, name + "_u")._data
        v = getattr(lyr, name + "_v")._data
        for _ in range(n_power_iterations):
            v = m.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = m @ v
            u = u / (jnp.linalg.norm(u) + eps)
        lyr._buffers[name + "_u"] = Tensor(u)
        lyr._buffers[name + "_v"] = Tensor(v)
        sigma = u @ m @ v
        import paddle_tpu as P
        t = orig / float(sigma)
        object.__setattr__(lyr, name, t)
        return None

    layer.register_forward_pre_hook(pre_hook)
    pre_hook(layer, None)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """In-place global grad-norm clip (reference clip_grad_norm_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    grads = [p._grad for p in params if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    flat = [jnp.reshape(g._data if hasattr(g, "_data") else g, (-1,))
            .astype(jnp.float32) for g in grads]
    if norm_type == float("inf"):
        total = jnp.max(jnp.concatenate([jnp.abs(f) for f in flat]))
    else:
        total = jnp.sum(jnp.concatenate(
            [jnp.abs(f) ** norm_type for f in flat])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError("gradient norm is non-finite")
    coef = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in params:
        if p._grad is not None:
            g = p._grad
            arr = g._data if hasattr(g, "_data") else g
            new = (arr.astype(jnp.float32) * coef).astype(arr.dtype)
            if hasattr(g, "_data"):
                g._data = new
            else:
                p._grad = new
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    """In-place grad value clip (reference clip_grad_value_.py)."""
    params = [parameters] if isinstance(parameters, Tensor) else \
        list(parameters)
    for p in params:
        if p._grad is not None:
            g = p._grad
            arr = g._data if hasattr(g, "_data") else g
            new = jnp.clip(arr, -clip_value, clip_value)
            if hasattr(g, "_data"):
                g._data = new
            else:
                p._grad = new


def parameters_to_vector(parameters, name=None):
    """(reference transform_parameters.py parameters_to_vector)"""
    params = list(parameters)
    return Tensor(jnp.concatenate(
        [jnp.reshape(p._data, (-1,)) for p in params]))


def vector_to_parameters(vec, parameters, name=None):
    """(reference transform_parameters.py vector_to_parameters)"""
    params = list(parameters)
    arr = vec._data if hasattr(vec, "_data") else jnp.asarray(vec)
    off = 0
    for p in params:
        n = int(np.prod(p._data.shape)) if p._data.shape else 1
        p._data = jnp.reshape(arr[off:off + n],
                              p._data.shape).astype(p._data.dtype)
        off += n
