"""paddle.nn.quant — weight-only quantization for serving.

Reference: python/paddle/nn/quant/quantized_linear.py (weight_quantize,
weight_dequantize, weight_only_linear, llm_int8_linear,
apply_per_channel_scale backed by CUTLASS mixed-dtype GEMMs,
paddle/phi/kernels/gpu/weight_only_linear_kernel.cu).

TPU formulation: int8 weights store as int8; int4 weights store
nibble-PACKED [K/2, N] (row 2k in the low nibble — the reference's
pack-along-K layout), so the HBM win is real: int8 halves and int4
quarters weight traffic.  The decode-shaped matmul runs the Pallas
weight-only GEMV kernel (ops/pallas/quant_matmul.py — the reference
weight_only_gemv.cu role); elsewhere XLA fuses the dequant
(cast * scale) into the matmul prologue.  Per-channel (group_size=-1)
or grouped (64/128) symmetric scales, matching the reference's
quantization math; there is no `arch` parameter — there is one target.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import op

__all__ = ["weight_quantize", "weight_dequantize", "weight_only_linear",
           "llm_int8_linear", "apply_per_channel_scale"]

_BOUNDS = {"weight_only_int8": 127.0, "weight_only_int4": 7.0,
           "llm.int8": 127.0}


def _check(algo, group_size):
    if algo not in _BOUNDS:
        raise ValueError(
            f"algo must be one of {sorted(_BOUNDS)}, got {algo!r}")
    if group_size not in (-1, 64, 128):
        raise ValueError(f"group_size must be -1, 64 or 128, "
                         f"got {group_size}")


@op
def weight_quantize(x, algo="weight_only_int8", arch=None, group_size=-1):
    """[K, N] float weight -> (quantized values, scales).

    int8: values [K, N] int8.  int4: values nibble-PACKED [K/2, N] int8
    (reference weight_quantize's pack-along-K layout — row 2k in the
    low nibble, row 2k+1 in the high).  Per-channel: scales [N];
    grouped: scales [K/group, N].  Symmetric (no zero point), like the
    reference kernels.
    """
    _check(algo, group_size)
    bound = _BOUNDS[algo]
    xf = x.astype(jnp.float32)
    k, n = xf.shape
    if algo == "weight_only_int4" and k % 2:
        raise ValueError(f"int4 packing needs even K, got {k}")
    if group_size == -1:
        absmax = jnp.max(jnp.abs(xf), axis=0)              # [N]
        scale = jnp.maximum(absmax / bound, 1e-8)
        q = jnp.clip(jnp.round(xf / scale), -bound, bound)
    else:
        if k % group_size:
            raise ValueError(f"K={k} not divisible by group {group_size}")
        g = xf.reshape(k // group_size, group_size, n)
        absmax = jnp.max(jnp.abs(g), axis=1)               # [K/g, N]
        scale = jnp.maximum(absmax / bound, 1e-8)
        q = jnp.clip(jnp.round(g / scale[:, None, :]), -bound, bound)
        q = q.reshape(k, n)
    q = q.astype(jnp.int8)
    if algo == "weight_only_int4":
        from ..ops.pallas.quant_matmul import pack_int4
        q = pack_int4(q)
    return q, scale.astype(jnp.float32)


@op
def weight_dequantize(x, scale, algo="weight_only_int8", group_size=-1):
    """Inverse of :func:`weight_quantize` (reference weight_dequantize) —
    for int4 the input is the packed [K/2, N] layout."""
    _check(algo, group_size)
    if algo == "weight_only_int4":
        from ..ops.pallas.quant_matmul import unpack_int4
        x = unpack_int4(x)
    xf = x.astype(jnp.float32)
    k, n = xf.shape
    if group_size == -1:
        return xf * scale
    if k % group_size:
        raise ValueError(f"K={k} not divisible by group {group_size}")
    return (xf.reshape(k // group_size, group_size, n)
            * scale[:, None, :]).reshape(k, n)


@op
def weight_only_linear(x, weight, bias=None, weight_scale=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """x [.., K] @ dequant(weight) + bias (int4 weights arrive packed
    [K/2, N], as :func:`weight_quantize` returns them).

    Per-channel scales route through the Pallas weight-only GEMV kernel
    (reference weight_only_linear_kernel.cu's mixed-dtype GEMM role) at
    decode shapes; grouped scales dequantize into the matmul prologue.
    """
    if weight_dtype not in ("int8", "int4"):
        raise ValueError(f"weight_dtype must be int8|int4, "
                         f"got {weight_dtype!r}")
    if weight_scale is None:
        raise ValueError("weight_only_linear requires weight_scale")
    algo = "weight_only_int8" if weight_dtype == "int8" \
        else "weight_only_int4"
    if group_size == -1:
        from ..ops.pallas.quant_matmul import (QuantizedWeight,
                                               weight_only_matmul)
        k = weight.shape[0] * (2 if weight_dtype == "int4" else 1)
        out = weight_only_matmul(
            x, QuantizedWeight(weight, weight_scale, kind=weight_dtype,
                               k=k))
    else:
        w = weight_dequantize.__op_body__(weight, weight_scale, algo,
                                          group_size).astype(x.dtype)
        out = x @ w
    if bias is not None:
        out = out + bias
    return out


@op
def llm_int8_linear(x, weight, bias=None, weight_scale=None,
                    threshold=6.0):
    """LLM.int8: activation columns whose absmax exceeds `threshold` run
    in the activation dtype against the DEQUANTIZED weight rows; the
    rest run int8 (reference llm_int8_linear / llm_int8_matmul_kernel).
    On TPU both branches lower to one masked matmul pair — the fidelity
    point is the outlier split, which this reproduces exactly.
    """
    if weight_scale is None:
        raise ValueError("llm_int8_linear requires weight_scale")
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf.reshape(-1, xf.shape[-1])), axis=0)
    outlier = absmax > threshold                           # [K]
    w = weight.astype(jnp.float32) * weight_scale          # [K, N]
    # inlier path: quantize activations to int8 per-tensor, int8 x int8
    x_in = jnp.where(outlier, 0.0, xf)
    a_scale = jnp.maximum(jnp.max(jnp.abs(x_in)) / 127.0, 1e-8)
    xq = jnp.clip(jnp.round(x_in / a_scale), -127, 127)
    inlier_out = (xq @ jnp.where(outlier[:, None], 0.0,
                                 weight.astype(jnp.float32))) \
        * a_scale * weight_scale
    # outlier path: full precision on the few outlier columns
    x_out = jnp.where(outlier, xf, 0.0)
    outlier_out = x_out @ jnp.where(outlier[:, None], w, 0.0)
    out = (inlier_out + outlier_out).astype(x.dtype)
    if bias is not None:
        out = out + bias
    return out


@op
def apply_per_channel_scale(x, scales):
    """Pre-quant activation smoothing: x / scales per channel (reference
    apply_per_channel_scale_kernel — activations divide by the smoothing
    scale that was folded into the weights)."""
    return (x.astype(jnp.float32) / scales.astype(jnp.float32)) \
        .astype(x.dtype)
