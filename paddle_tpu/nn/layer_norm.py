"""Normalization layers (reference: python/paddle/nn/layer/norm.py).

BatchNorm keeps running stats as buffers; in train mode forward rebinds the
buffers to the updated stats (functional under jit via the Layer state
bridge)."""
from __future__ import annotations

import numpy as np

from .layer import Layer
from .layer_common import Layer as _L  # noqa
from . import functional as F
from .initializer import Constant
from ..framework.tensor import Tensor

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "GroupNorm",
           "InstanceNorm1D", "InstanceNorm2D", "InstanceNorm3D",
           "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_features], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter(
            [num_features], attr=bias_attr, is_bias=True)
        from ..ops.creation import zeros, ones
        self.register_buffer("_mean", zeros([num_features]))
        self.register_buffer("_variance", ones([num_features]))

    def forward(self, x):
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)
        from ..framework.tensor import Tensor
        if self.training and not self._use_global_stats:
            if isinstance(new_mean, Tensor):
                self._mean._rebind_(new_mean.detach())
                self._variance._rebind_(new_var.detach())
            else:
                # static build: record the running-stat write-back so the
                # Executor applies it after each run (reference: the
                # stat-update ops static batch_norm appends in-graph)
                prog = new_mean.program
                prog.stat_updates.append((self._mean, new_mean))
                prog.stat_updates.append((self._variance, new_var))
                prog.version += 1
        return out

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Under SPMD/GSPMD batch stats are computed over the full (global)
    batch automatically when the batch axis is sharded — XLA inserts the
    cross-replica reductions (reference: sync_batch_norm NCCL kernel
    paddle/phi/kernels/gpu/sync_batch_norm_kernel.cu)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            new = cls(layer._num_features, layer._momentum, layer._epsilon,
                      data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._mean = layer._mean
            new._variance = layer._variance
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                self._normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """TPU-first: fused Pallas rmsnorm (reference fused_rms_norm)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter(
            [hidden_size], attr=weight_attr, default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self.weight, self.bias,
                            self._epsilon, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
        else:
            self.scale = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(
                [num_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        import jax.numpy as jnp
        from ..ops.creation import randn
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter([h])
        self.weight_v = self.create_parameter([w])

    def forward(self, weight):
        import jax.numpy as jnp
        from ..ops import math as m
        from .. import ops
        w_mat = weight.transpose(
            [self._dim] + [i for i in range(weight.ndim) if i != self._dim])
        h = w_mat.shape[0]
        w_mat = w_mat.reshape([h, -1])
        u, v = self.weight_u, self.weight_v
        for _ in range(self._power_iters):
            v = F.normalize(w_mat.T.matmul(u), axis=0, epsilon=self._epsilon)
            u = F.normalize(w_mat.matmul(v), axis=0, epsilon=self._epsilon)
        sigma = u.matmul(w_mat).matmul(v)
        return weight / sigma
