"""Beam-search decoding: Decoder / BeamSearchDecoder / dynamic_decode.

Reference: python/paddle/nn/decode.py (BeamSearchDecoder:161,
dynamic_decode:1238).  Semantics mirrored exactly: scores are summed
log-softmax probabilities, finished beams emit only end_token with
log-prob 0 (so their score freezes), top-k runs over the flattened
[beam_size * vocab] candidates, and finalize back-tracks the beam
ancestry with gather_tree.

TPU formulation: every step is fixed-shape tensor work ([B, K, V]
top-k merge — no ragged hypotheses sets), so the loop body jits; the
eager loop stops early on all-finished exactly like the reference's
imperative path.
"""
from __future__ import annotations

import collections

import numpy as np

from ..framework.tensor import Tensor
from . import functional as F

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _map_structure(fn, *structs):
    import jax
    return jax.tree_util.tree_map(
        fn, *structs, is_leaf=lambda x: isinstance(x, Tensor))


def _flatten(struct):
    import jax
    return jax.tree_util.tree_flatten(
        struct, is_leaf=lambda x: isinstance(x, Tensor))[0]


class Decoder:
    """Base decoder interface for dynamic_decode (reference decode.py:50):
    initialize() -> (input, state, finished); step() -> (output, state,
    next_input, finished); optional finalize()."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """reference decode.py:161 — wraps a cell; each step scores
    candidates and keeps the top ``beam_size`` hypotheses per batch."""

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn
        self.start_token = start_token
        self.end_token = end_token
        self.beam_size = beam_size
        self.kinf = 1e9

    # ----------------------------------------------------- shape helpers
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[batch, ...] -> [batch * beam_size, ...] by tiling each batch
        entry (for encoder outputs used inside cell.call)."""
        import paddle_tpu as paddle
        x = paddle.unsqueeze(x, [1])
        tiles = [1, beam_size] + [1] * (len(x.shape) - 2)
        x = paddle.tile(x, tiles)
        return paddle.reshape(x, [-1] + list(x.shape[2:]))

    def _expand_to_beam_size(self, x):
        import paddle_tpu as paddle
        x = paddle.unsqueeze(x, [1])
        tiles = [1, self.beam_size] + [1] * (len(x.shape) - 2)
        return paddle.tile(x, tiles)

    def _merge_batch_beams(self, x):
        import paddle_tpu as paddle
        return paddle.reshape(x, [-1] + list(x.shape[2:]))

    def _split_batch_beams(self, x):
        import paddle_tpu as paddle
        return paddle.reshape(x, [-1, self.beam_size] + list(x.shape[1:]))

    def _gather(self, x, indices, batch_size):
        """Per-batch gather along the beam axis."""
        import paddle_tpu as paddle
        batch_pos = paddle.tile(
            paddle.unsqueeze(paddle.arange(0, batch_size, 1,
                                           dtype=indices.dtype), [1]),
            [1, self.beam_size])
        coords = paddle.stack([batch_pos, indices], axis=2)
        return paddle.gather_nd(x, coords)

    # ------------------------------------------------------------- steps
    def initialize(self, initial_cell_states):
        import paddle_tpu as paddle
        state = _flatten(initial_cell_states)[0]
        self.batch_size = int(state.shape[0])

        init_cell_states = _map_structure(self._expand_to_beam_size,
                                          initial_cell_states)
        init_inputs = paddle.full([self.batch_size, self.beam_size],
                                  self.start_token, "int64")
        log_probs = paddle.tile(
            paddle.to_tensor(
                np.array([[0.0] + [-self.kinf] * (self.beam_size - 1)],
                         dtype="float32")),
            [self.batch_size, 1])
        init_finished = paddle.full([self.batch_size, self.beam_size],
                                    False, "bool")
        init_lengths = paddle.zeros_like(init_inputs)
        if self.embedding_fn is not None:
            init_inputs = self.embedding_fn(init_inputs)
        return (init_inputs,
                self.StateWrapper(init_cell_states, log_probs,
                                  init_finished, init_lengths),
                init_finished)

    def _mask_probs(self, probs, finished):
        """Finished beams: only end_token continues, with log-prob 0."""
        import paddle_tpu as paddle
        noend = np.full((self.vocab_size,), -self.kinf, "float32")
        noend[self.end_token] = 0.0
        noend_t = paddle.to_tensor(noend)
        fin = paddle.cast(finished, probs.dtype)
        return probs * (1.0 - fin.unsqueeze([2])) \
            + noend_t.reshape([1, 1, -1]) * fin.unsqueeze([2])

    def _beam_search_step(self, time, logits, next_cell_states, beam_state):
        import paddle_tpu as paddle
        self.vocab_size = int(logits.shape[-1])

        step_log_probs = paddle.log(F.softmax(logits))
        step_log_probs = self._mask_probs(step_log_probs,
                                          beam_state.finished)
        log_probs = step_log_probs + beam_state.log_probs.unsqueeze([2])
        scores = paddle.reshape(log_probs,
                                [-1, self.beam_size * self.vocab_size])
        topk_scores, topk_indices = paddle.topk(scores, k=self.beam_size)
        beam_indices = topk_indices // self.vocab_size
        token_indices = topk_indices % self.vocab_size
        next_log_probs = self._gather(scores, topk_indices,
                                      self.batch_size)
        next_cell_states = _map_structure(
            lambda x: self._gather(x, beam_indices, self.batch_size),
            next_cell_states)
        next_finished = self._gather(beam_state.finished, beam_indices,
                                     self.batch_size)
        next_lengths = self._gather(beam_state.lengths, beam_indices,
                                    self.batch_size)
        next_lengths = next_lengths + paddle.cast(
            paddle.logical_not(next_finished), next_lengths.dtype)
        next_finished = paddle.logical_or(
            next_finished,
            paddle.equal(token_indices,
                         paddle.full([1], self.end_token, "int64")))

        return (self.OutputWrapper(topk_scores, token_indices,
                                   beam_indices),
                self.StateWrapper(next_cell_states, next_log_probs,
                                  next_finished, next_lengths))

    def step(self, time, inputs, states, **kwargs):
        inputs = _map_structure(self._merge_batch_beams, inputs)
        cell_states = _map_structure(self._merge_batch_beams,
                                     states.cell_states)
        cell_outputs, next_cell_states = self.cell(inputs, cell_states,
                                                   **kwargs)
        cell_outputs = _map_structure(self._split_batch_beams,
                                      cell_outputs)
        next_cell_states = _map_structure(self._split_batch_beams,
                                          next_cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)

        beam_search_output, beam_search_state = self._beam_search_step(
            time, cell_outputs, next_cell_states, states)
        finished = beam_search_state.finished
        sample_ids = beam_search_output.predicted_ids
        if self.embedding_fn is not None:
            next_inputs = self.embedding_fn(sample_ids)
        else:
            next_inputs = sample_ids
        return beam_search_output, beam_search_state, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        """Back-track beam ancestry (gather_tree) to materialize the
        predicted token sequences [time, batch, beam]."""
        if outputs.predicted_ids.shape[0] == 0:
            # zero decode steps: no ancestry to backtrack, and
            # gather_tree cannot consume an empty time axis
            return outputs.predicted_ids, final_states
        predicted_ids = F.gather_tree(outputs.predicted_ids,
                                      outputs.parent_ids)
        return predicted_ids, final_states

    def empty_outputs(self):
        """Zero-step output structure (time dimension 0, time-major) for
        dynamic_decode's zero-iteration path."""
        import paddle_tpu as paddle
        shp = [0, self.batch_size, self.beam_size]
        return self.OutputWrapper(paddle.zeros(shp, "float32"),
                                  paddle.zeros(shp, "int64"),
                                  paddle.zeros(shp, "int64"))

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference decode.py:1238 — run decoder.step until every sequence
    finishes or max_step_num is reached; stack per-step outputs."""
    import paddle_tpu as paddle

    initial_inputs, initial_states, initial_finished = \
        decoder.initialize(inits)
    inputs, states, finished = (initial_inputs, initial_states,
                                paddle.cast(initial_finished, "bool"))
    cond = paddle.logical_not(paddle.all(finished))
    sequence_lengths = paddle.cast(paddle.zeros_like(finished), "int64")
    outputs_list = None
    step_idx = 0

    while bool(cond.numpy()) and (max_step_num is None
                                  or step_idx <= max_step_num):
        time = paddle.to_tensor(np.array([step_idx], "int64"))
        (step_outputs, next_states, next_inputs,
         next_finished) = decoder.step(time, inputs, states, **kwargs)
        if not decoder.tracks_own_finished:
            next_finished = paddle.logical_or(next_finished, finished)
        # reference: every beam still running at this step's start gets
        # length = step+1 (lengths freeze only once finished)
        next_sequence_lengths = paddle.where(
            paddle.logical_not(finished),
            paddle.full_like(sequence_lengths, step_idx + 1),
            sequence_lengths)
        if impute_finished:
            float_mask = paddle.cast(finished, "float32")

            def _impute(new, old):
                if new.dtype not in (old.dtype,):
                    return new
                m = float_mask
                while len(m.shape) < len(new.shape):
                    m = m.unsqueeze([-1])
                m = paddle.cast(m, new.dtype) \
                    if "float" in str(new.dtype) else None
                if m is None:
                    return new
                return new * (1.0 - m) + old * m

            next_states = _map_structure(_impute, next_states, states)

        flat_out = _flatten(step_outputs)
        if outputs_list is None:
            outputs_list = [[o] for o in flat_out]
        else:
            for acc, o in zip(outputs_list, flat_out):
                acc.append(o)
        inputs, states, finished = next_inputs, next_states, next_finished
        sequence_lengths = next_sequence_lengths
        cond = paddle.logical_not(paddle.all(finished))
        step_idx += 1

    if outputs_list is None:
        # zero iterations (every beam already finished at initialize, or
        # max_step_num < 0): there are no step outputs to stack — return
        # explicitly empty outputs (time dimension 0) instead of tripping
        # a NameError on the never-assigned per-step locals.  Nothing ran,
        # so there is no beam ancestry to finalize either.
        empty = getattr(decoder, "empty_outputs", None)
        if empty is None:
            raise ValueError(
                "dynamic_decode ran zero decode steps (all sequences "
                "finished at initialize, or max_step_num < 0) and "
                f"{type(decoder).__name__} does not implement "
                "empty_outputs(); cannot synthesize an empty output "
                "structure")
        final_outputs = empty()
        final_states = states
        if hasattr(decoder, "finalize") and not is_test:
            try:
                final_outputs, final_states = decoder.finalize(
                    final_outputs, final_states, sequence_lengths)
            except NotImplementedError:
                pass
    else:
        import jax
        _, treedef = jax.tree_util.tree_flatten(
            step_outputs, is_leaf=lambda x: isinstance(x, Tensor))
        stacked = [paddle.stack(acc, axis=0) for acc in outputs_list]
        final_outputs = jax.tree_util.tree_unflatten(treedef, stacked)
        final_states = states

        if hasattr(decoder, "finalize") and not is_test:
            try:
                final_outputs, final_states = decoder.finalize(
                    final_outputs, final_states, sequence_lengths)
            except NotImplementedError:
                pass

    if not output_time_major:
        final_outputs = _map_structure(
            lambda x: paddle.transpose(
                x, [1, 0] + list(range(2, len(x.shape)))),
            final_outputs)

    return ((final_outputs, final_states, sequence_lengths)
            if return_length else (final_outputs, final_states))
