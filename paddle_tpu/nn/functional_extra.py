"""nn.functional, part 2 — pooling/conv/loss surface completing parity with
python/paddle/nn/functional/{pooling,conv,loss,activation}.py.

Everything is a registered framework op over pure jax bodies; window ops use
lax.reduce_window (XLA tiles these), unpool/fractional use gather/scatter.
CTC (reference phi/kernels/cpu/ctc_align & warpctc binding) and RNNT
(third_party/warprnnt) are implemented natively as log-space dynamic programs
with lax.scan — no vendor library.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import op
from ..framework import random as _random
from .functional import _pair, _conv_padding, _reduce, _ceil_pads
from ..ops.math_extra import unflatten  # noqa: F401  (shared op)

__all__ = [
    "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d", "adaptive_max_pool1d",
    "adaptive_max_pool3d", "lp_pool1d", "lp_pool2d", "fractional_max_pool2d",
    "fractional_max_pool3d", "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "conv1d_transpose", "conv3d_transpose", "dropout3d",
    "feature_alpha_dropout", "log_sigmoid", "thresholded_relu", "unflatten",
    "gaussian_nll_loss", "poisson_nll_loss", "multi_margin_loss",
    "multi_label_soft_margin_loss", "soft_margin_loss",
    "triplet_margin_with_distance_loss", "ctc_loss", "rnnt_loss",
    "hsigmoid_loss", "max_pool2d_with_index",
]


# ------------------------------------------------------------------ pooling

def _window_cfg(k, s, pads, nd):
    window = (1, 1) + k
    strides = (1, 1) + s
    # string padding ('SAME'/'VALID') passes straight through to reduce_window
    pad_cfg = pads if isinstance(pads, str) \
        else [(0, 0), (0, 0)] + list(pads)
    return window, strides, pad_cfg


@op
def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if data_format == "NDHWC":
        out = max_pool3d.__op_body__(
            jnp.transpose(x, (0, 4, 1, 2, 3)), kernel_size, stride, padding,
            ceil_mode, return_mask, "NCDHW")
        if return_mask:
            return (jnp.transpose(out[0], (0, 2, 3, 4, 1)),
                    jnp.transpose(out[1], (0, 2, 3, 4, 1)))
        return jnp.transpose(out, (0, 2, 3, 4, 1))
    k = _pair(kernel_size, 3)
    s = _pair(stride if stride is not None else kernel_size, 3)
    pads = _conv_padding(padding, 3)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:5], k, s)
    if return_mask:
        return _pool_argmax(x, k, s, pads)
    window, strides, pad_cfg = _window_cfg(k, s, pads, 3)
    neg = np.asarray(-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                     else np.iinfo(x.dtype).min, x.dtype)
    return jax.lax.reduce_window(x, neg, jax.lax.max, window, strides,
                                 pad_cfg)


@op
def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    if data_format == "NDHWC":
        out = avg_pool3d.__op_body__(
            jnp.transpose(x, (0, 4, 1, 2, 3)), kernel_size, stride, padding,
            ceil_mode, exclusive, divisor_override, "NCDHW")
        return jnp.transpose(out, (0, 2, 3, 4, 1))
    k = _pair(kernel_size, 3)
    s = _pair(stride if stride is not None else kernel_size, 3)
    pads = _conv_padding(padding, 3)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:5], k, s)
    window, strides, pad_cfg = _window_cfg(k, s, pads, 3)
    summed = jax.lax.reduce_window(x, np.zeros((), x.dtype), jax.lax.add,
                                   window, strides, pad_cfg)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        counts = jax.lax.reduce_window(jnp.ones_like(x),
                                       np.zeros((), x.dtype), jax.lax.add,
                                       window, strides, pad_cfg)
        return summed / counts
    return summed / (k[0] * k[1] * k[2])


def _adaptive_pool_nd(x, output_size, nd, reducer):
    """Variable-window adaptive pool over the trailing nd spatial axes."""
    spatial = x.shape[-nd:]
    out_sizes = _pair(output_size, nd)

    def pool_axis(a, in_s, out_s, axis):
        if in_s % out_s == 0:
            r = in_s // out_s
            shp = list(a.shape)
            shp[axis:axis + 1] = [out_s, r]
            return reducer(a.reshape(shp), axis + 1)
        starts = (np.arange(out_s) * in_s) // out_s
        ends = ((np.arange(out_s) + 1) * in_s + out_s - 1) // out_s
        pieces = [reducer(jax.lax.slice_in_dim(a, int(st), int(en), axis=axis),
                          axis, keepdims=True)
                  for st, en in zip(starts, ends)]
        return jnp.concatenate(pieces, axis=axis)

    ax0 = x.ndim - nd
    for i in range(nd):
        x = pool_axis(x, spatial[i], out_sizes[i], ax0 + i)
    return x


@op
def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_pool_nd(
        x, output_size, 3,
        lambda a, ax, keepdims=False: jnp.mean(a, axis=ax, keepdims=keepdims))


@op
def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool3d(return_mask=True) is not supported; use "
            "max_pool3d(..., return_mask=True) for unpool indices")
    return _adaptive_pool_nd(
        x, output_size, 3,
        lambda a, ax, keepdims=False: jnp.max(a, axis=ax, keepdims=keepdims))


@op
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "adaptive_max_pool1d(return_mask=True) is not supported; use "
            "max_pool1d(..., return_mask=True) for unpool indices")
    return _adaptive_pool_nd(
        x, output_size, 1,
        lambda a, ax, keepdims=False: jnp.max(a, axis=ax, keepdims=keepdims))


@op
def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    k = _pair(kernel_size, 1)
    s = _pair(stride if stride is not None else kernel_size, 1)
    pads = _conv_padding(padding, 1)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:3], k, s)
    window, strides, pad_cfg = _window_cfg(k, s, pads, 1)
    p = float(norm_type)
    if math.isinf(p):
        neg = np.asarray(-np.inf, x.dtype)
        return jax.lax.reduce_window(jnp.abs(x), neg, jax.lax.max,
                                     window, strides, pad_cfg)
    summed = jax.lax.reduce_window(jnp.abs(x) ** p, np.zeros((), x.dtype),
                                   jax.lax.add, window, strides, pad_cfg)
    return summed ** (1.0 / p)


@op
def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCHW", name=None):
    k = _pair(kernel_size, 2)
    s = _pair(stride if stride is not None else kernel_size, 2)
    pads = _conv_padding(padding, 2)
    if ceil_mode:
        pads = _ceil_pads(pads, x.shape[2:4], k, s)
    window, strides, pad_cfg = _window_cfg(k, s, pads, 2)
    p = float(norm_type)
    if math.isinf(p):
        neg = np.asarray(-np.inf, x.dtype)
        return jax.lax.reduce_window(jnp.abs(x), neg, jax.lax.max,
                                     window, strides, pad_cfg)
    summed = jax.lax.reduce_window(jnp.abs(x) ** p, np.zeros((), x.dtype),
                                   jax.lax.add, window, strides, pad_cfg)
    return summed ** (1.0 / p)


def _fractional_bounds(in_s, out_s, u):
    """Graham fractional pooling boundaries: b_i = ceil(alpha*(i+u)) clipped,
    with windows [b_i, b_{i+1})."""
    alpha = in_s / out_s
    idx = np.arange(out_s + 1, dtype=np.float64)
    b = np.ceil(alpha * (idx + u)).astype(np.int64) - int(np.ceil(alpha * u))
    b = np.clip(b, 0, in_s)
    b[0], b[-1] = 0, in_s
    return b


def _fractional_pool(x, output_size, random_u, nd):
    out_sizes = _pair(output_size, nd)
    if random_u is None:
        random_u = float(jax.random.uniform(_random.split_key(), ()))
    ax0 = x.ndim - nd
    for i in range(nd):
        in_s = x.shape[ax0 + i]
        b = _fractional_bounds(in_s, out_sizes[i], random_u)
        pieces = [jnp.max(jax.lax.slice_in_dim(
            x, int(b[j]), int(max(b[j + 1], b[j] + 1)), axis=ax0 + i),
            axis=ax0 + i, keepdims=True) for j in range(out_sizes[i])]
        x = jnp.concatenate(pieces, axis=ax0 + i)
    return x


@op
def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool2d(return_mask=True) is not supported")
    return _fractional_pool(x, output_size, random_u, 2)


@op
def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "fractional_max_pool3d(return_mask=True) is not supported")
    return _fractional_pool(x, output_size, random_u, 3)


# ------------------------------------------------- max pool w/ index, unpool

def _pool_argmax(x, k, s, pads):
    """Max pool returning (values, flat spatial argmax) for the trailing
    len(k) spatial axes (reference max_pool2d_with_index kernel)."""
    nd = len(k)
    if isinstance(pads, str):
        if pads != "VALID":
            raise ValueError("return_mask pooling supports int padding only")
        pads = [(0, 0)] * nd
    spatial = x.shape[-nd:]
    pad_width = [(0, 0)] * (x.ndim - nd) + list(pads)
    neg = np.asarray(-np.inf if jnp.issubdtype(x.dtype, jnp.floating)
                     else np.iinfo(x.dtype).min, x.dtype)
    xp = jnp.pad(x, pad_width, constant_values=neg)
    # flat index of each padded position in the ORIGINAL (unpadded) map
    grids = jnp.meshgrid(*[jnp.arange(xp.shape[-nd + i]) - pads[i][0]
                           for i in range(nd)], indexing="ij")
    flat = jnp.zeros_like(grids[0])
    for i in range(nd):
        flat = flat * spatial[i] + jnp.clip(grids[i], 0, spatial[i] - 1)
    flat = flat.astype(jnp.int32)
    # gather windows: out_shape x prod(k)
    out_sp = [ (xp.shape[-nd + i] - k[i]) // s[i] + 1 for i in range(nd)]
    vals, idxs = [], []
    for offs in np.ndindex(*k):
        sl = tuple([slice(None)] * (x.ndim - nd) +
                   [slice(offs[i], offs[i] + (out_sp[i] - 1) * s[i] + 1, s[i])
                    for i in range(nd)])
        vals.append(xp[sl])
        idxs.append(jnp.broadcast_to(flat[tuple(
            slice(offs[i], offs[i] + (out_sp[i] - 1) * s[i] + 1, s[i])
            for i in range(nd))], xp[sl].shape))
    v = jnp.stack(vals, axis=-1)
    ix = jnp.stack(idxs, axis=-1)
    amax = jnp.argmax(v, axis=-1)
    out = jnp.take_along_axis(v, amax[..., None], axis=-1)[..., 0]
    out_idx = jnp.take_along_axis(ix, amax[..., None], axis=-1)[..., 0]
    return out, out_idx


@op
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, name=None):
    k = _pair(kernel_size, 2)
    s = _pair(stride if stride is not None else kernel_size, 2)
    pads = _conv_padding(padding, 2)
    return _pool_argmax(x, k, s, pads)


def _max_unpool(x, indices, nd, kernel_size, stride, padding, output_size,
                data_format):
    if data_format in ("NLC", "NHWC", "NDHWC"):  # channels-last: recurse NCX
        perm_in = (0, nd + 1) + tuple(range(1, nd + 1))
        perm_out = (0,) + tuple(range(2, nd + 2)) + (1,)
        out = _max_unpool(jnp.transpose(x, perm_in),
                          jnp.transpose(indices, perm_in), nd, kernel_size,
                          stride, padding, output_size, "NC" + "X" * nd)
        return jnp.transpose(out, perm_out)
    k = _pair(kernel_size, nd)
    s = _pair(stride if stride is not None else kernel_size, nd)
    p = _pair(padding, nd)
    in_sp = x.shape[-nd:]
    if output_size is None:
        out_sp = [ (in_sp[i] - 1) * s[i] - 2 * p[i] + k[i] for i in range(nd)]
    else:
        out_sp = list(_pair(output_size, nd))[-nd:]
    lead = x.shape[:-nd]
    total = int(np.prod(out_sp))
    xf = x.reshape(lead + (-1,))
    idxf = indices.reshape(lead + (-1,)).astype(jnp.int32)
    flat_lead = int(np.prod(lead)) if lead else 1
    xf2 = xf.reshape(flat_lead, -1)
    idx2 = idxf.reshape(flat_lead, -1)
    out = jnp.zeros((flat_lead, total), x.dtype)
    out = jax.vmap(lambda o, i, v: o.at[i].set(v))(out, idx2, xf2)
    return out.reshape(lead + tuple(out_sp))


@op
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
    return _max_unpool(x, indices, 1, kernel_size, stride, padding,
                       output_size, data_format)


@op
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
    return _max_unpool(x, indices, 2, kernel_size, stride, padding,
                       output_size, data_format)


@op
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
    return _max_unpool(x, indices, 3, kernel_size, stride, padding,
                       output_size, data_format)


# ----------------------------------------------------------- transposed conv

def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       groups, dilation, nd, spec, output_size=None):
    strides = _pair(stride, nd)
    pads = _conv_padding(padding, nd)
    dil = _pair(dilation, nd)
    opad = _pair(output_padding, nd)
    if output_size is not None and not isinstance(pads, str):
        # paddle semantics: output_size disambiguates the strided-transpose
        # shape; realize it as extra trailing output padding
        want = _pair(output_size, nd)[-nd:]
        opad = list(opad)
        for i in range(nd):
            default = ((x.shape[2 + i] - 1) * strides[i] - pads[i][0]
                       - pads[i][1] + dil[i] * (weight.shape[2 + i] - 1) + 1)
            extra = int(want[i]) - default
            if extra < 0 or extra >= strides[i]:
                raise ValueError(
                    f"invalid output_size {want[i]} for dim {i}: reachable "
                    f"range is [{default}, {default + strides[i] - 1}]")
            opad[i] = opad[i] + extra
        opad = tuple(opad)
    w = jnp.swapaxes(weight, 0, 1)  # paddle [in, out/g, *k] -> [out/g, in, *k]
    if isinstance(pads, str):
        padding_cfg = pads
    else:
        padding_cfg = [
            (dil[i] * (weight.shape[2 + i] - 1) - pads[i][0],
             dil[i] * (weight.shape[2 + i] - 1) - pads[i][1] + opad[i])
            for i in range(nd)]
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, spec)
    w_flip = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    ones = (1,) * nd
    if groups > 1:
        xs = jnp.split(x, groups, axis=1)
        ws = jnp.split(w_flip, groups, axis=0)
        outs = [jax.lax.conv_general_dilated(
            xi, wi, window_strides=ones, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
            for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=1)
    else:
        out = jax.lax.conv_general_dilated(
            x, w_flip, window_strides=ones, padding=padding_cfg,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
    if bias is not None:
        out = out + bias.reshape((1, -1) + ones)
    return out


@op
def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCL", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 1,
                              ("NCH", "OIH", "NCH"), output_size)


@op
def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1, output_size=None,
                     data_format="NCDHW", name=None):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, groups, dilation, 3,
                              ("NCDHW", "OIDHW", "NCDHW"), output_size)


# ---------------------------------------------------------------- dropout &c

@op
def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    if not training or p == 0.0:
        return x
    c_axis = 1 if data_format == "NCDHW" else 4
    shape = [x.shape[0], 1, 1, 1, 1]
    shape[c_axis] = x.shape[c_axis]
    keep = jax.random.bernoulli(_random.split_key(), 1.0 - p, tuple(shape))
    return jnp.where(keep, x / (1.0 - p), jnp.zeros((), x.dtype))


@op
def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    alpha_p = -1.7580993408473766
    shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
    keep = jax.random.bernoulli(_random.split_key(), 1.0 - p, shape)
    a = 1.0 / math.sqrt((alpha_p ** 2 * p + 1) * (1 - p))
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, jnp.asarray(alpha_p, x.dtype)) + b


@op
def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(x)


@op
def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return jnp.where(x > threshold, x, jnp.asarray(value, x.dtype))


# -------------------------------------------------------------------- losses

@op
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + jnp.square(input - label) / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce(loss, reduction)


@op
def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling approximation for label! (only where label > 1)
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * math.pi * label))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@op
def soft_margin_loss(input, label, reduction="mean", name=None):
    loss = jnp.log1p(jnp.exp(-label.astype(input.dtype) * input))
    return _reduce(loss, reduction)


@op
def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean",
                                 name=None):
    y = label.astype(input.dtype)
    loss = -(y * jax.nn.log_sigmoid(input)
             + (1 - y) * jax.nn.log_sigmoid(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


@op
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    n, c = input.shape
    correct = jnp.take_along_axis(input, label[:, None], axis=1)
    m = jnp.maximum(margin - correct + input, 0.0)
    if p != 1:
        m = m ** p
    if weight is not None:
        m = m * weight[label][:, None]
    mask = jax.nn.one_hot(label, c, dtype=input.dtype)
    loss = jnp.sum(m * (1 - mask), axis=1) / c
    return _reduce(loss, reduction)


@op
def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean", name=None):
    if distance_function is None:
        distance_function = lambda a, b: jnp.linalg.norm(a - b, axis=-1)
    d_pos = distance_function(input, positive)
    d_neg = distance_function(input, negative)
    if swap:
        d_neg = jnp.minimum(d_neg, distance_function(positive, negative))
    loss = jnp.maximum(d_pos - d_neg + margin, 0.0)
    return _reduce(loss, reduction)


@op
def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """Native CTC (reference binds warpctc: paddle/phi/kernels/impl/
    warpctc_kernel_impl.h).  log_probs [T, N, C] logits (softmax applied
    here), labels [N, L]."""
    lp = jax.nn.log_softmax(log_probs, axis=-1)
    T, N, C = lp.shape
    L = labels.shape[1]
    S = 2 * L + 1
    neg_inf = -1e30

    def per_sample(lp_n, lab, t_len, l_len):
        ext = jnp.full((S,), blank, labels.dtype)
        ext = ext.at[1::2].set(lab)
        emit = lp_n[:, ext]  # [T, S]
        same = jnp.concatenate([jnp.ones((2,), bool), ext[2:] == ext[:-2]])
        valid_s = jnp.arange(S) < 2 * l_len + 1
        alpha0 = jnp.full((S,), neg_inf)
        alpha0 = alpha0.at[0].set(emit[0, 0])
        alpha0 = alpha0.at[1].set(
            jnp.where(l_len > 0, emit[0, 1], neg_inf))

        def step(carry, inp):
            alpha, t = carry
            e = inp
            a1 = jnp.concatenate([jnp.full((1,), neg_inf), alpha[:-1]])
            a2 = jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]])
            a2 = jnp.where(same, neg_inf, a2)
            new = jnp.logaddexp(jnp.logaddexp(alpha, a1), a2) + e
            new = jnp.where(valid_s, new, neg_inf)
            # freeze once past this sample's input length
            new = jnp.where(t < t_len, new, alpha)
            return (new, t + 1), None

        (alpha, _), _ = jax.lax.scan(step, (alpha0, jnp.asarray(1)), emit[1:])
        end1 = alpha[jnp.maximum(2 * l_len - 1, 0)]
        end2 = alpha[2 * l_len]
        ll = jnp.logaddexp(jnp.where(l_len > 0, end1, neg_inf), end2)
        return -ll

    losses = jax.vmap(per_sample, in_axes=(1, 0, 0, 0))(
        lp, labels, input_lengths, label_lengths)
    if reduction == "mean":
        return jnp.mean(losses / jnp.maximum(label_lengths, 1))
    return _reduce(losses, reduction)


@op
def rnnt_loss(logits, labels, logit_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """Native RNN-T loss (reference binds warprnnt: phi/kernels/impl/
    warprnnt_kernel_impl.h).  logits [N, T, U+1, C], labels [N, U].

    FastEmit (arXiv:2010.11148): the emission branches of the lattice
    get their gradients scaled by (1 + lambda) while the loss VALUE and
    the blank-branch gradients stay those of the standard transducer
    NLL — warprnnt's gradient-level rescaling.  Realized functionally as
    loss + lambda * (M - stop_gradient(M)) where M recomputes the NLL
    with the blank lattice probabilities detached, so d(M) flows only
    through the emit branches.
    """
    lp = jax.nn.log_softmax(logits, axis=-1)
    N, T, U1, C = lp.shape
    U = U1 - 1
    neg_inf = -1e30

    def per_sample(lp_n, lab, t_len, u_len):
        blank_lp = lp_n[:, :, blank]                       # [T, U+1]
        emit_lp = jnp.take_along_axis(
            lp_n[:, :U, :], lab[None, :, None].astype(jnp.int32),
            axis=2)[..., 0]                                # [T, U]
        nll = _transducer_nll(blank_lp, emit_lp, t_len, u_len, U1,
                              neg_inf)
        if fastemit_lambda:
            m = _transducer_nll(jax.lax.stop_gradient(blank_lp),
                                emit_lp, t_len, u_len, U1, neg_inf)
            nll = nll + fastemit_lambda * (m - jax.lax.stop_gradient(m))
        return nll

    losses = jax.vmap(per_sample)(lp, labels, logit_lengths,
                                  label_lengths)
    return _reduce(losses, reduction)


def _transducer_nll(blank_lp, emit_lp, t_len, u_len, U1, neg_inf):
    """One sample's transducer negative log-likelihood from the lattice
    log-probs blank_lp [T, U+1] / emit_lp [T, U]."""
    T = blank_lp.shape[0]
    if T:
        u_idx = jnp.arange(U1)

        def t_step(alpha_prev, inp):
            t, blank_row, emit_row = inp
            # alpha[t, u] from alpha[t-1, u] (blank) then left-to-right u scan
            from_blank = alpha_prev + blank_row            # [U+1]

            def u_step(carry, inp_u):
                u, fb, em_prev = inp_u
                val = jnp.where(u == 0, fb,
                                jnp.logaddexp(fb, carry + em_prev))
                return val, val

            em_prev = jnp.concatenate([jnp.zeros((1,)), emit_row])  # pad u=0
            _, alpha_t = jax.lax.scan(
                u_step, neg_inf, (u_idx, from_blank, em_prev))
            alpha_t = jnp.where(u_idx <= u_len, alpha_t, neg_inf)
            alpha_t = jnp.where(t < t_len, alpha_t, alpha_prev)
            return alpha_t, alpha_t

        # alpha[0, u]: only via emits along u
        def u0_step(carry, inp_u):
            u, em_prev = inp_u
            val = jnp.where(u == 0, 0.0, carry + em_prev)
            return val, val

        em_prev0 = jnp.concatenate([jnp.zeros((1,)), emit_lp[0]])
        _, alpha0 = jax.lax.scan(u0_step, 0.0, (u_idx, em_prev0))
        alpha0 = jnp.where(u_idx <= u_len, alpha0, neg_inf)

        ts = jnp.arange(1, T)
        # alpha[t,u] = logaddexp(alpha[t-1,u] + blank(t-1,u),
        #                        alpha[t,u-1] + emit(t,u-1))
        alpha_T, _ = jax.lax.scan(
            t_step, alpha0, (ts, blank_lp[:-1], emit_lp[1:]))
        # final: alpha[t_len-1, u_len] + blank(t_len-1, u_len)
        ll = alpha_T[u_len] + blank_lp[jnp.maximum(t_len - 1, 0), u_len]
        return -ll


@op
def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid over the default complete binary tree
    (reference phi/kernels/cpu/hsigmoid_loss_kernel.cc; matrix_bit_code.h
    encodes class c as the path of node (c + num_classes) back to root)."""
    if path_table is not None:
        codes = path_code
        table = path_table
        depth = table.shape[1]
        rows = table.astype(jnp.int32)
        valid = rows >= 0
        rows = jnp.maximum(rows, 0)
    else:
        depth = max(int(np.ceil(np.log2(max(num_classes, 2)))) + 1, 1)
        node = label.astype(jnp.int32) + num_classes
        rows_l, codes_l = [], []
        for _ in range(depth):
            parent = node // 2
            codes_l.append((node % 2).astype(jnp.float32))
            rows_l.append(parent - 1)
            node = parent
        rows = jnp.stack(rows_l, axis=-1)
        codes = jnp.stack(codes_l, axis=-1)
        valid = rows >= 0
        rows = jnp.maximum(rows, 0)
    w = weight[rows]                       # [N, depth, D]
    logits = jnp.einsum("nd,nkd->nk", input, w)
    if bias is not None:
        logits = logits + bias.reshape(-1)[rows]
    codes = codes.astype(logits.dtype)
    # BCE with the path bit as target: softplus(z) - code*z
    per_node = -jax.nn.log_sigmoid((2.0 * codes - 1.0) * logits)
    per_node = jnp.where(valid, per_node, 0.0)
    return jnp.sum(per_node, axis=-1, keepdims=True)
