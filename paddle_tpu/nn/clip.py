"""Gradient clipping (reference: python/paddle/nn/clip.py —
ClipGradByGlobalNorm etc., applied inside optimizer.step; the hybrid-parallel
variant reduces the norm across mesh axes, see
distributed/fleet/hybrid_optimizer.py)."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["ClipGradBase", "ClipGradByValue", "ClipGradByNorm",
           "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            elif hasattr(g, "_sq_norm"):  # RowSparseGrad: clip value rows
                m = g.merged()
                out.append((p, type(g)(m.rows,
                                       jnp.clip(m.values, self.min, self.max),
                                       m.dense_shape)))
            else:
                out.append((p, jnp.clip(g, self.min, self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            n = jnp.sqrt(g._sq_norm() if hasattr(g, "_sq_norm") else
                         jnp.sum(jnp.square(g.astype(jnp.float32))))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(n, 1e-12), 1.0)
            out.append((p, (g.astype(jnp.float32) * factor).astype(g.dtype)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def global_norm(self, grads):
        # RowSparseGrad contributes the norm of its dense equivalent
        # (duplicate rows merged first)
        sq = [g._sq_norm() if hasattr(g, "_sq_norm")
              else jnp.sum(jnp.square(g.astype(jnp.float32)))
              for g in grads if g is not None]
        if not sq:
            return jnp.asarray(0.0, jnp.float32)
        return jnp.sqrt(sum(sq))

    def __call__(self, params_grads):
        gn = self.global_norm([g for p, g in params_grads
                               if g is not None and getattr(p, "need_clip", True)])
        factor = jnp.minimum(self.clip_norm / jnp.maximum(gn, 1e-12), 1.0)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
            else:
                out.append((p, (g.astype(jnp.float32) * factor).astype(g.dtype)))
        return out
