"""paddle.nn namespace (reference: python/paddle/nn/__init__.py)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import Layer, Parameter, ParamAttr  # noqa: F401
from .layer_common import *  # noqa: F401,F403
from .layer_conv import Conv1D, Conv2D, Conv3D, Conv2DTranspose  # noqa: F401
from .layer_norm import *  # noqa: F401,F403
from .layer_pool import *  # noqa: F401,F403
from .layer_loss import *  # noqa: F401,F403
from .layer_moe import MoELayer  # noqa: F401
from .layer_rnn import (  # noqa: F401
    RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN, BiRNN, SimpleRNN,
    LSTM, GRU)
from .layer_extra import *  # noqa: F401,F403
from .transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer)
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
from . import utils  # noqa: F401
from . import quant  # noqa: F401
from .clip import ClipGradByNorm, ClipGradByValue, ClipGradByGlobalNorm  # noqa: F401
