"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .layer import Layer
from . import functional as F
from .initializer import XavierUniform, Constant


def _tuple(v, n):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v),) * n


class _ConvNd(Layer):
    def __init__(self, nd, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format=None,
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _tuple(kernel_size, nd)
        self.stride = _tuple(stride, nd)
        self.padding = padding
        self.dilation = _tuple(dilation, nd)
        self.groups = groups
        self.padding_mode = padding_mode
        self.data_format = data_format
        self.output_padding = output_padding
        self._transpose = transpose
        if transpose:
            w_shape = [in_channels, out_channels // groups, *self.kernel_size]
        else:
            w_shape = [out_channels, in_channels // groups, *self.kernel_size]
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        bound = 1.0 / np.sqrt(fan_in)
        from .initializer import Uniform
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr, default_initializer=Uniform(-bound, bound))
        self.bias = self.create_parameter(
            [out_channels], attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={list(self.kernel_size)}, stride={list(self.stride)}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, padding_mode, weight_attr,
                         bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, stride=self.stride,
                        padding=self.padding, dilation=self.dilation,
                        groups=self.groups, data_format=self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(
            x, self.weight, self.bias, stride=self.stride,
            padding=self.padding, output_padding=self.output_padding,
            groups=self.groups, dilation=self.dilation,
            output_size=output_size, data_format=self.data_format)
