"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from __future__ import annotations

from .layer import Layer
from . import functional as F

__all__ = ["MaxPool1D", "MaxPool2D", "AvgPool1D", "AvgPool2D",
           "AdaptiveAvgPool1D", "AdaptiveAvgPool2D", "AdaptiveMaxPool2D"]


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        k, s, p, cm, rm, df = self.args
        return F.max_pool2d(x, k, stride=s, padding=p, ceil_mode=cm,
                            return_mask=rm, data_format=df)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        k, s, p, cm, ex, dv, df = self.args
        return F.avg_pool2d(x, k, stride=s, padding=p, ceil_mode=cm,
                            exclusive=ex, divisor_override=dv, data_format=df)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        k, s, p, rm, cm = self.args
        return F.max_pool1d(x, k, stride=s, padding=p, return_mask=rm,
                            ceil_mode=cm)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        k, s, p, ex, cm = self.args
        return F.avg_pool1d(x, k, stride=s, padding=p, exclusive=ex,
                            ceil_mode=cm)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size,
                                     data_format=self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size,
                                     return_mask=self.return_mask)
