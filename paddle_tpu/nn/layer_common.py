"""Common layers (reference: python/paddle/nn/layer/common.py, container.py,
activation.py)."""
from __future__ import annotations

import collections

import numpy as np

from .layer import Layer, Parameter, ParamAttr
from . import functional as F
from ..framework.tensor import Tensor, to_tensor

__all__ = [
    "Linear", "Embedding", "Dropout", "Dropout2D", "AlphaDropout", "Flatten",
    "Identity", "Upsample", "UpsamplingBilinear2D", "UpsamplingNearest2D",
    "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D", "CosineSimilarity", "Bilinear",
    "Sequential", "LayerList", "ParameterList", "LayerDict",
    "Softmax2D", "ChannelShuffle", "PairwiseDistance", "Fold",
    "ReLU", "ReLU6", "GELU", "Sigmoid", "Tanh", "Softmax", "LogSoftmax",
    "LeakyReLU", "ELU", "SELU", "CELU", "PReLU", "RReLU", "Hardswish",
    "Hardsigmoid", "Hardtanh", "Hardshrink", "Softshrink", "Tanhshrink",
    "Softplus", "Softsign", "Mish", "Silu", "Swish", "GLU", "Maxout",
    "PixelShuffle", "PixelUnshuffle", "Unfold",
]


class Linear(Layer):
    """y = x @ W + b, W: [in_features, out_features] (reference:
    python/paddle/nn/layer/common.py Linear)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr)
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        from .initializer import XavierNormal, Normal
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.sparse = sparse  # row-sparse weight grads (SelectedRows analog)
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=Normal(0.0, 1.0))
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx,
                           sparse=self.sparse)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return x.flatten(self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners
        self.align_mode = align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners,
                             align_mode=self.align_mode,
                             data_format=self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import pad
        return pad(x, self.padding, mode=self.mode, value=self.value,
                   data_format=self.data_format)


class Pad1D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad2D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__(padding, mode, value, data_format, name)


class Pad3D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format, name)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format, name)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


# ------------------------------------------------------------- containers

# Eager segment tracing toggle (reference hot-path goal, phi/README.md
# §1.2).  The machinery is GENERAL — Layer._segment_call (layer.py)
# runs a hook/buffer-free composite layer's forward as ONE cached-jit
# dispatch with dynamic purity probing (eager-RNG / untraceable python
# falls back per-op).  On a tunneled transport each eager dispatch costs
# ~0.5 ms, so this is the dygraph forward's dispatch-count lever.
#
# Auto-segmenting by DEFAULT applies only to framework-defined layer
# types (classes living under the paddle_tpu package): a user
# subclass's hand-written forward may read mutable Python state that
# the purity probe cannot see, which would be baked into the first
# trace and silently replayed stale.  User subclasses opt in per class
# with ``segment_forward = True`` (and a framework type can opt out
# with ``segment_forward = False``); the decision is cached per class.
SEGMENT_FORWARD = True
_SEG_IDS = iter(range(1, 1 << 62))
_SEG_ELIGIBLE: dict = {}        # class -> cached eligibility


def segment_eligible(cls) -> bool:
    """Is ``cls`` allowed to auto-segment?  An explicit class-level
    ``segment_forward`` attribute anywhere in the MRO wins; otherwise
    only framework-defined types (``paddle_tpu.*`` modules) qualify."""
    cached = _SEG_ELIGIBLE.get(cls)
    if cached is None:
        flag = getattr(cls, "segment_forward", None)
        if flag is not None:
            cached = bool(flag)
        else:
            cached = ((cls.__module__ or "").split(".", 1)[0]
                      == "paddle_tpu")
        _SEG_ELIGIBLE[cls] = cached
    return cached


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], collections.OrderedDict):
            for name, layer in layers[0].items():
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return list(self._parameters.values())[idx]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __len__(self):
        return len(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()


# ------------------------------------------------- activation layer shims

def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            merged.pop("name", None)
            names = list(defaults.keys())
            for i, a in enumerate(args):
                merged[names[i]] = a
            for k, v in kwargs.items():
                if k != "name":
                    merged[k] = v
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", F.relu)
ReLU6 = _act_layer("ReLU6", F.relu6)
GELU = _act_layer("GELU", F.gelu, approximate=False)
Sigmoid = _act_layer("Sigmoid", F.sigmoid)
Tanh = _act_layer("Tanh", F.tanh)
Softmax = _act_layer("Softmax", F.softmax, axis=-1)
LogSoftmax = _act_layer("LogSoftmax", F.log_softmax, axis=-1)
LeakyReLU = _act_layer("LeakyReLU", F.leaky_relu, negative_slope=0.01)
ELU = _act_layer("ELU", F.elu, alpha=1.0)
SELU = _act_layer("SELU", F.selu)
CELU = _act_layer("CELU", F.celu, alpha=1.0)
RReLU = _act_layer("RReLU", F.rrelu, lower=1.0 / 8.0, upper=1.0 / 3.0)
Hardswish = _act_layer("Hardswish", F.hardswish)
Hardsigmoid = _act_layer("Hardsigmoid", F.hardsigmoid)
Hardtanh = _act_layer("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
Hardshrink = _act_layer("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _act_layer("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _act_layer("Tanhshrink", F.tanhshrink)
Softplus = _act_layer("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _act_layer("Softsign", F.softsign)
Mish = _act_layer("Mish", F.mish)
Silu = _act_layer("Silu", F.silu)
Swish = _act_layer("Swish", F.silu)
GLU = _act_layer("GLU", F.glu, axis=-1)
Maxout = _act_layer("Maxout", F.maxout, groups=2, axis=1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        from .initializer import Constant
        self.data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, data_format=self.data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold_(x, *self.args)


class Softmax2D(Layer):
    """Softmax over the channel dim of NCHW input (reference:
    python/paddle/nn/layer/activation.py Softmax2D)."""

    def forward(self, x):
        if x.ndim not in (3, 4):
            raise ValueError(f"Softmax2D expects 3D/4D input, got {x.ndim}D")
        return F.softmax(x, axis=-3)


class ChannelShuffle(Layer):
    """Reference: python/paddle/nn/layer/vision.py ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        from ..ops.manipulation import reshape, transpose
        g = self.groups
        if self.data_format == "NCHW":
            b, c, h, w = x.shape
            x = reshape(x, [b, g, c // g, h, w])
            x = transpose(x, [0, 2, 1, 3, 4])
            return reshape(x, [b, c, h, w])
        b, h, w, c = x.shape
        x = reshape(x, [b, h, w, g, c // g])
        x = transpose(x, [0, 1, 2, 4, 3])
        return reshape(x, [b, h, w, c])


class PairwiseDistance(Layer):
    """Reference: python/paddle/nn/layer/distance.py."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ..ops.linalg import norm as _norm
        d = x - y + self.epsilon
        return _norm(d, p=self.p, axis=-1, keepdim=self.keepdim)


class Fold(Layer):
    """Inverse of Unfold: [B, C*kh*kw, L] -> [B, C, H, W] by summing
    overlapping patches (reference: python/paddle/nn/layer/common.py
    Fold; kernel fold_kernel)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        from .functional import _pair
        self.output_sizes = _pair(output_sizes)
        self.kernel_sizes = _pair(kernel_sizes)
        self.strides = _pair(strides)
        self.paddings = _pair(paddings)
        self.dilations = _pair(dilations)

    def forward(self, x):
        return F.fold(x, self.output_sizes, self.kernel_sizes,
                      self.strides, self.paddings, self.dilations)
