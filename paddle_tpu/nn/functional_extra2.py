"""nn.functional, part 3 — vision warps, ArcFace ops, beam-search utils,
flash-attention packed/masked entry points (reference:
python/paddle/nn/functional/{vision,common,extension,loss,flash_attention}.py).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.registry import op
from ..framework import random as _random
from .functional import _reduce, scaled_dot_product_attention

__all__ = [
    "affine_grid", "grid_sample", "channel_shuffle", "zeropad2d",
    "sequence_mask", "gather_tree", "dice_loss", "sigmoid_focal_loss",
    "pairwise_distance", "class_center_sample", "margin_cross_entropy",
    "adaptive_log_softmax_with_loss", "flash_attn_qkvpacked",
    "flash_attn_varlen_qkvpacked", "flashmask_attention", "sparse_attention",
]


# ------------------------------------------------------------ vision warps

@op
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Affine sampling grid (reference nn/functional/vision.py:38;
    phi/kernels/impl/affine_grid_kernel_impl.h)."""
    out_shape = [int(s) for s in np.asarray(out_shape).reshape(-1)]
    nd = len(out_shape) - 2  # 2 (HW) or 3 (DHW)

    def axis_coords(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    if nd == 2:
        n, _, h, w = out_shape
        ys = axis_coords(h)
        xs = axis_coords(w)
        xg, yg = jnp.meshgrid(xs, ys, indexing="xy")
        ones = jnp.ones_like(xg)
        base = jnp.stack([xg, yg, ones], axis=-1)      # [H, W, 3]
        return jnp.einsum("hwk,nck->nhwc", base, theta)
    n, _, d, h, w = out_shape
    zs = axis_coords(d)
    ys = axis_coords(h)
    xs = axis_coords(w)
    zg, yg, xg = jnp.meshgrid(zs, ys, xs, indexing="ij")
    ones = jnp.ones_like(xg)
    base = jnp.stack([xg, yg, zg, ones], axis=-1)      # [D, H, W, 4]
    return jnp.einsum("dhwk,nck->ndhwc", base, theta)


@op
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x at normalized grid locations (reference
    nn/functional/vision.py:140; phi grid_sample kernels).  4-D and 5-D."""
    nd = x.ndim - 2

    def unnorm(g, size):
        if align_corners:
            return (g + 1.0) / 2.0 * (size - 1)
        return ((g + 1.0) * size - 1.0) / 2.0

    def reflect(v, size):
        if align_corners:
            span = 2 * (size - 1)
            v = jnp.abs(v) % jnp.maximum(span, 1)
            return jnp.where(v > size - 1, span - v, v)
        # reflect around the -0.5 / size-0.5 pixel borders
        span = 2 * size
        v = jnp.abs(v + 0.5) % span
        v = jnp.minimum(v, span - v) - 0.5
        return jnp.clip(v, 0, size - 1)

    def resolve(v, size):
        if padding_mode == "border":
            return jnp.clip(v, 0, size - 1), None
        if padding_mode == "reflection":
            return reflect(v, size), None
        valid = (v >= -1) & (v <= size)
        return v, valid  # zeros handled by corner validity below

    sizes = x.shape[2:]
    coords = [unnorm(grid[..., i], sizes[nd - 1 - i]) for i in range(nd)]
    coords = coords[::-1]  # now ordered like spatial dims (d, h, w)/(h, w)

    if mode == "nearest":
        idxs = []
        for v, size in zip(coords, sizes):
            if padding_mode != "zeros":
                v, _ = resolve(v, size)
            v = jnp.round(v)
            vi = jnp.clip(v, 0, size - 1).astype(jnp.int32)
            idxs.append((vi, (v >= 0) & (v <= size - 1)))
        valid = jnp.ones(idxs[0][0].shape, bool)
        for _, vl in idxs:
            valid &= vl
        def gather_n(img, *ii):
            return img[(slice(None),) + tuple(ii)]
        out = jax.vmap(gather_n)(x, *[i for i, _ in idxs])
        if padding_mode == "zeros":
            out = jnp.where(
                jnp.expand_dims(valid, 1), out, jnp.zeros((), x.dtype))
        return out

    # bilinear / trilinear: accumulate the 2^nd corners
    lo_w = []
    for v, size in zip(coords, sizes):
        if padding_mode != "zeros":
            v, _ = resolve(v, size)
        v0 = jnp.floor(v)
        lo_w.append((v0, v - v0))
    out = 0.0
    for corner in range(2 ** nd):
        idxs, wgt, valid = [], 1.0, True
        for axis in range(nd):
            hi = (corner >> axis) & 1
            v0, frac = lo_w[axis]
            vv = v0 + hi
            size = sizes[axis]
            valid = valid & (vv >= 0) & (vv <= size - 1)
            idxs.append(jnp.clip(vv, 0, size - 1).astype(jnp.int32))
            wgt = wgt * (frac if hi else (1 - frac))
        def gather_c(img, *ii):
            return img[(slice(None),) + tuple(ii)]
        vals = jax.vmap(gather_c)(x, *idxs)
        w_eff = jnp.where(valid, wgt, 0.0) if padding_mode == "zeros" \
            else wgt
        out = out + vals * jnp.expand_dims(w_eff, 1)
    return out


@op
def channel_shuffle(x, groups, data_format="NCHW", name=None):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w) \
            .swapaxes(1, 2).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups) \
        .swapaxes(3, 4).reshape(n, h, w, c)


@op
def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ..ops.manipulation import pad as _pad
    return _pad.__op_body__(x, padding, mode="constant", value=0.0,
                            data_format=data_format)


# --------------------------------------------------------- sequence utils

@op
def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        maxlen = int(jnp.max(x))
    from ..framework.dtype import to_np_dtype
    rng_ = jnp.arange(maxlen)
    return (rng_ < x[..., None]).astype(to_np_dtype(dtype))


@op
def gather_tree(ids, parents, name=None):
    """Beam-search backtrace (reference nn/functional/extension.py:149;
    phi/kernels/cpu/gather_tree_kernel.cc).  ids/parents:
    [max_time, batch, beam]."""
    T = ids.shape[0]

    def step(carry, inp):
        beam_idx, t = carry, inp
        id_t = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        parent_t = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return parent_t, id_t

    init = jnp.broadcast_to(jnp.arange(ids.shape[2]), ids.shape[1:])
    _, out_rev = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return out_rev[::-1]


# ----------------------------------------------------------------- losses

@op
def dice_loss(input, label, epsilon=1e-05, name=None):
    """(reference nn/functional/loss.py:50): input [.., D] probabilities,
    label [.., 1] class ids."""
    d = input.shape[-1]
    one = jax.nn.one_hot(label[..., 0], d, dtype=input.dtype)
    reduce_dims = tuple(range(1, input.ndim))
    inter = jnp.sum(input * one, axis=reduce_dims)
    union = jnp.sum(input, axis=reduce_dims) + jnp.sum(one, axis=reduce_dims)
    dice = (2 * inter + epsilon) / (union + epsilon)
    return jnp.mean(1 - dice)


@op
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """(reference nn/functional/loss.py:3262)."""
    p = jax.nn.sigmoid(logit)
    ce = jnp.maximum(logit, 0) - logit * label \
        + jnp.log1p(jnp.exp(-jnp.abs(logit)))
    p_t = p * label + (1 - p) * (1 - label)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        a_t = alpha * label + (1 - alpha) * (1 - label)
        loss = a_t * loss
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


@op
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    d = x - y + epsilon
    return jnp.linalg.norm(jnp.abs(d), ord=p, axis=-1, keepdims=keepdim) \
        if p != 2.0 else jnp.sqrt(
            jnp.sum(jnp.square(d), axis=-1, keepdims=keepdim))


# ------------------------------------------------------------- ArcFace ops

def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample negative class centers for partial-FC training (reference
    nn/functional/common.py:2372; phi class_center_sample kernel).
    Returns (remapped_label, sampled_class_indices).  Host-side sampling —
    eager only."""
    import numpy as _np
    from ..framework.tensor import Tensor
    lab = _np.asarray(label.numpy() if hasattr(label, "numpy") else label)
    pos = _np.unique(lab)
    n_extra = max(int(num_samples) - len(pos), 0)
    rest = _np.setdiff1d(_np.arange(num_classes), pos)
    rng_ = _np.random.default_rng(int(_np.abs(lab).sum()) + num_classes)
    neg = rng_.choice(rest, size=min(n_extra, len(rest)), replace=False) \
        if n_extra else _np.zeros((0,), lab.dtype)
    sampled = _np.concatenate([pos, _np.sort(neg)]).astype(lab.dtype)
    remap = {c: i for i, c in enumerate(sampled.tolist())}
    remapped = _np.asarray([remap[int(c)] for c in lab], lab.dtype)
    return Tensor(jnp.asarray(remapped)), Tensor(jnp.asarray(sampled))


@op
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace/CosFace margin softmax CE (reference nn/functional/
    loss.py:2183; phi margin_cross_entropy kernel): logits are cos(theta),
    target class gets cos(m1*theta + m2) - m3 before scaling."""
    n, c = logits.shape
    cos_t = jnp.clip(logits, -1.0, 1.0)
    theta = jnp.arccos(cos_t)
    target_logit = jnp.cos(margin1 * theta + margin2) - margin3
    one = jax.nn.one_hot(label, c, dtype=logits.dtype)
    adjusted = jnp.where(one > 0, target_logit, cos_t) * scale
    logp = jax.nn.log_softmax(adjusted, axis=-1)
    loss = -jnp.sum(one * logp, axis=-1, keepdims=True)
    loss = _reduce(loss, reduction)
    if return_softmax:
        return loss, jnp.exp(logp)
    return loss


def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """Functional form of AdaptiveLogSoftmaxWithLoss (reference
    nn/functional/activation.py adaptive_log_softmax_with_loss)."""
    import paddle_tpu
    from .functional import linear, log_softmax
    cutoffs = list(cutoffs)
    shortlist = cutoffs[0]
    n_clusters = len(cutoffs) - 1 if cutoffs[-1] >= shortlist else 0
    head_lp = log_softmax(linear(input, head_weight, head_bias), axis=-1)
    lab = label.astype("int32")
    in_head = (lab < shortlist).astype("float32")
    safe = lab.clip(0, shortlist - 1)
    out = head_lp.take_along_axis(safe.reshape((-1, 1)), 1).reshape((-1,)) \
        * in_head
    for i in range(len(tail_weights)):
        lo = cutoffs[i]
        hi = cutoffs[i + 1]
        mask = ((lab >= lo).astype("float32")
                * (lab < hi).astype("float32"))
        rel = (lab - lo).clip(0, hi - lo - 1)
        h = input
        for w in tail_weights[i]:
            h = h.matmul(w)
        tail_lp = log_softmax(h, axis=-1)
        take = tail_lp.take_along_axis(rel.reshape((-1, 1)), 1).reshape((-1,))
        out = out + (head_lp[:, shortlist + i] + take) * mask
    return out, -(out.mean())


# ------------------------------------------------------- flash attn surface

def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, *, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """Packed-QKV flash attention (reference flash_attention.py:399).
    qkv: [batch, seqlen, 3, num_heads, head_dim] -> (out, softmax)."""
    if return_softmax:
        raise NotImplementedError(
            "return_softmax=True requires materializing the [S, S] matrix "
            "the flash kernel exists to avoid")
    q = qkv[:, :, 0]
    k = qkv[:, :, 1]
    v = qkv[:, :, 2]
    out = scaled_dot_product_attention(q, k, v, dropout_p=dropout,
                                       is_causal=causal, training=training)
    return out, None


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q, max_seqlen_k, scale=None,
                                dropout=0.0, causal=False,
                                return_softmax=False, varlen_padded=True,
                                **kw):
    """Varlen packed flash attention (reference flash_attention.py):
    total-token layout [total, 3, heads, dim] with cu_seqlens offsets.
    Computed per sequence via segment masking."""
    import paddle_tpu
    if return_softmax:
        raise NotImplementedError("return_softmax not supported")
    cu = np.asarray(cu_seqlens_q.numpy() if hasattr(cu_seqlens_q, "numpy")
                    else cu_seqlens_q).reshape(-1)
    head_dim = int(qkv.shape[-1])
    # sdpa scales by 1/sqrt(d); realize a custom scale by pre-scaling q
    q_mult = (scale * math.sqrt(head_dim)) if scale is not None else 1.0
    outs = []
    for i in range(len(cu) - 1):
        seg = qkv[int(cu[i]):int(cu[i + 1])]
        q = seg[:, 0][None] * q_mult
        k = seg[:, 1][None]
        v = seg[:, 2][None]
        o = scaled_dot_product_attention(q, k, v, dropout_p=dropout,
                                         is_causal=causal)
        outs.append(o[0])
    return paddle_tpu.concat(outs, axis=0), None


@op
def flashmask_attention(query, key, value, startend_row_indices=None, *,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """FlashMask attention (reference flash_attention.py:1098): column-wise
    sparse mask given as start/end row indices per key column.  Routed to
    the Pallas interval-mask kernels (ops/pallas/flash_mask.py) — O(S)
    mask memory, no [S,S] score matrix; the dense fallback below covers
    CPU/odd shapes/dropout."""
    if return_softmax_lse or return_seed_offset:
        raise NotImplementedError("lse/seed outputs not supported")
    b, sq, hq, d = query.shape
    sk = key.shape[1]
    if startend_row_indices is None:
        from ..ops.pallas import flash_attention as _fa
        return _fa.sdpa(query, key, value, dropout_p=dropout,
                        is_causal=causal, training=training)
    idx = startend_row_indices  # [B, H or 1, Sk, k]
    kdim = idx.shape[-1]

    # kernel path: translate the reference encoding into mask_vecs
    # [B, H|1, nvec, Sk] (intervals of MASKED rows per key column)
    vecs = None
    moved = jnp.moveaxis(jnp.asarray(idx), -1, 2)       # [B, H, k, Sk]
    if causal and kdim == 1:
        lts = moved[:, :, 0]
        vecs = jnp.stack([lts, jnp.full_like(lts, sq)], axis=2)
    elif causal and kdim == 2:
        vecs = moved
    elif not causal and kdim == 2:
        lts, ute = moved[:, :, 0], moved[:, :, 1]
        vecs = jnp.stack([lts, jnp.full_like(lts, sq),
                          jnp.zeros_like(lts), ute], axis=2)
    elif not causal and kdim == 4:
        vecs = moved
    if vecs is not None and window_size is not None and causal:
        # causal sliding window: column j masked for rows > j + left
        left = window_size if isinstance(window_size, int) else \
            window_size[0]
        col = jnp.broadcast_to(jnp.arange(sk, dtype=vecs.dtype),
                               vecs.shape[:2] + (sk,))
        vecs = jnp.concatenate(
            [vecs, jnp.stack([col + left + 1,
                              jnp.full_like(col, sq)], axis=2)], axis=2)
    if window_size is not None and not causal:
        raise NotImplementedError(
            "flashmask_attention window_size requires causal=True "
            "(the reference's sliding windows are causal)")
    if vecs is not None:
        from ..ops.pallas import flash_attention as _fa
        return _fa.sdpa(query, key, value, dropout_p=dropout,
                        is_causal=causal, training=training,
                        flashmask=vecs.astype(jnp.int32))
    rows = jnp.arange(sq)[:, None]                      # i (query/row)
    if causal:
        if kdim == 1:
            lts = idx[..., 0]                           # [B,H,Sk]
            masked = rows[None, None] >= lts[:, :, None, :]
        elif kdim == 2:
            lts = idx[..., 0]
            lte = idx[..., 1]
            masked = ((rows[None, None] >= lts[:, :, None, :])
                      & (rows[None, None] < lte[:, :, None, :]))
        else:
            raise ValueError("causal flashmask expects 1 or 2 indices")
        cols = jnp.arange(sk)[None, :]
        causal_mask = rows < cols                       # future masked
        masked = masked | causal_mask[None, None]
    else:
        if kdim == 2:
            lts = idx[..., 0]
            ute = idx[..., 1]
            masked = ((rows[None, None] >= lts[:, :, None, :])
                      | (rows[None, None] < ute[:, :, None, :]))
        elif kdim == 4:
            lts, lte, uts, ute = (idx[..., i] for i in range(4))
            masked = (((rows[None, None] >= lts[:, :, None, :])
                       & (rows[None, None] < lte[:, :, None, :]))
                      | ((rows[None, None] >= uts[:, :, None, :])
                         & (rows[None, None] < ute[:, :, None, :])))
        else:
            raise ValueError("non-causal flashmask expects 2 or 4 indices")
    bias = jnp.where(masked, jnp.asarray(-1e9, query.dtype),
                     jnp.asarray(0.0, query.dtype))    # [B, H, Sq, Sk]
    q = jnp.swapaxes(query, 1, 2)
    k = jnp.swapaxes(key, 1, 2)
    v = jnp.swapaxes(value, 1, 2)
    if bias.shape[1] == 1:
        bias = jnp.broadcast_to(bias, (b, hq, sq, sk))
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d) + bias
    probs = jax.nn.softmax(scores, axis=-1)
    if dropout and training:
        keep = jax.random.bernoulli(_random.split_key(), 1 - dropout,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1 - dropout), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, v)
    return jnp.swapaxes(out, 1, 2)


def sparse_attention(*args, **kwargs):
    raise NotImplementedError(
        "sparse_attention binds a CUDA-only blocksparse kernel in the "
        "reference (nn/functional/sparse_attention.py); use "
        "flashmask_attention or scaled_dot_product_attention on TPU")
