"""Recurrent layers: SimpleRNN / LSTM / GRU (+ cells, RNN/BiRNN wrappers).

Reference: python/paddle/nn/layer/rnn.py (SimpleRNNCell/LSTMCell/GRUCell,
RNN :56, BiRNN, SimpleRNN/LSTM/GRU multi-layer stacks) with Paddle's
parameter layout (weight_ih [gate_size, input_size], weight_hh
[gate_size, hidden_size], gate order i,f,c,o for LSTM and r,z,c for GRU)
and `sequence_length` masking semantics.

TPU formulation: each full time-loop is ONE op — a `jax.lax.scan` over
the (static-shape) time axis, so XLA compiles a single fused loop body
instead of Python-unrolled steps; masking for variable-length sequences
is a `where` against the carried step index (no dynamic shapes).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import functional as F
from .layer import Layer
from ..framework.tensor import Tensor
from ..ops.registry import op

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU"]


# ----------------------------------------------------------- pure scan ops
def _mask_step(t, seq_len, new, old):
    """new where t < seq_len (per batch row) else old."""
    if seq_len is None:
        return new
    m = (t < seq_len)[:, None]
    return jnp.where(m, new, old)


def _scan_rnn(step, x, init, seq_len, reverse):
    """x: [T, B, I] time-major. step(carry, xt, t) -> (carry, yt)."""
    T = x.shape[0]
    ts = jnp.arange(T)
    if reverse:
        x = x[::-1]
        ts = ts[::-1]

    def body(carry, xt_t):
        xt, t = xt_t
        return step(carry, xt, t)

    carry, ys = jax.lax.scan(body, init, (x, ts))
    if reverse:
        ys = ys[::-1]
    return carry, ys


@op
def simple_rnn_scan(x, h0, w_ih, w_hh, b_ih, b_hh, seq_len=None,
                    reverse=False, activation="tanh"):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu

    def step(h, xt, t):
        hn = act(xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh)
        hn = _mask_step(t, seq_len, hn, h)
        y = _mask_step(t, seq_len, hn, jnp.zeros_like(hn))
        return hn, y

    h, ys = _scan_rnn(step, x, h0, seq_len, reverse)
    return ys, h


@op
def lstm_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh, seq_len=None,
              reverse=False):
    def step(carry, xt, t):
        h, c = carry
        gates = xt @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        cn = f * c + i * g
        hn = o * jnp.tanh(cn)
        hn = _mask_step(t, seq_len, hn, h)
        cn = _mask_step(t, seq_len, cn, c)
        y = _mask_step(t, seq_len, hn, jnp.zeros_like(hn))
        return (hn, cn), y

    (h, c), ys = _scan_rnn(step, x, (h0, c0), seq_len, reverse)
    return ys, h, c


@op
def gru_scan(x, h0, w_ih, w_hh, b_ih, b_hh, seq_len=None, reverse=False):
    def step(h, xt, t):
        xg = xt @ w_ih.T + b_ih
        hg = h @ w_hh.T + b_hh
        xr, xz, xc = jnp.split(xg, 3, axis=-1)
        hr, hz, hc = jnp.split(hg, 3, axis=-1)
        r = jax.nn.sigmoid(xr + hr)
        z = jax.nn.sigmoid(xz + hz)
        c = jnp.tanh(xc + r * hc)
        hn = z * h + (1.0 - z) * c
        hn = _mask_step(t, seq_len, hn, h)
        y = _mask_step(t, seq_len, hn, jnp.zeros_like(hn))
        return hn, y

    h, ys = _scan_rnn(step, x, h0, seq_len, reverse)
    return ys, h


# ------------------------------------------------------------------ cells
class RNNCellBase(Layer):
    """Reference: python/paddle/nn/layer/rnn.py RNNCellBase (state init)."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        state_shape = self.state_shape
        if isinstance(state_shape[0], (list, tuple)):
            return tuple(
                Tensor(jnp.full((batch,) + tuple(s), init_value,
                                self._param_dtype()))
                for s in state_shape)
        return Tensor(jnp.full((batch,) + tuple(state_shape), init_value,
                               self._param_dtype()))

    def _param_dtype(self):
        return self.weight_ih._data.dtype

    def _make_params(self, gate_size, input_size, hidden_size, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr):
        from .initializer import Uniform
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [gate_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [gate_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [gate_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [gate_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        if activation not in ("tanh", "relu"):
            raise ValueError("activation must be tanh or relu")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.activation = activation
        self._make_params(hidden_size, input_size, hidden_size,
                          weight_ih_attr, weight_hh_attr, bias_ih_attr,
                          bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = F.tanh if self.activation == "tanh" else F.relu
        h = act(F.linear(inputs, self.weight_ih.t(), self.bias_ih)
                + F.linear(states, self.weight_hh.t(), self.bias_hh))
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(4 * hidden_size, input_size, hidden_size,
                          weight_ih_attr, weight_hh_attr, bias_ih_attr,
                          bias_hh_attr)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states
        out = _lstm_cell_step(inputs, h, c, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh)
        hn, cn = out
        return hn, (hn, cn)


@op
def _lstm_cell_step(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + b_ih + h @ w_hh.T + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    cn = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    hn = jax.nn.sigmoid(o) * jnp.tanh(cn)
    return hn, cn


@op
def _gru_cell_step(x, h, w_ih, w_hh, b_ih, b_hh):
    xr, xz, xc = jnp.split(x @ w_ih.T + b_ih, 3, axis=-1)
    hr, hz, hc = jnp.split(h @ w_hh.T + b_hh, 3, axis=-1)
    r = jax.nn.sigmoid(xr + hr)
    z = jax.nn.sigmoid(xz + hz)
    c = jnp.tanh(xc + r * hc)
    return z * h + (1.0 - z) * c


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self._make_params(3 * hidden_size, input_size, hidden_size,
                          weight_ih_attr, weight_hh_attr, bias_ih_attr,
                          bias_hh_attr)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h = _gru_cell_step(inputs, states, self.weight_ih, self.weight_hh,
                           self.bias_ih, self.bias_hh)
        return h, h


# --------------------------------------------------------------- wrappers
class RNN(Layer):
    """Run a cell over a sequence (reference rnn.py:56). Python time loop
    (arbitrary user cells can't be scanned); the SimpleRNN/LSTM/GRU stacks
    below use the fused lax.scan ops instead."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ..ops.manipulation import stack

        def map_states(fn, new, old):
            if isinstance(new, (tuple, list)):
                return type(new)(
                    map_states(fn, n, o) for n, o in zip(new, old))
            return fn(new, old)

        time_axis = 0 if self.time_major else 1
        T = inputs.shape[time_axis]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        if states is None:
            ref = inputs if self.time_major else inputs.transpose(
                [1, 0] + list(range(2, inputs.ndim)))
            states = self.cell.get_initial_states(ref, batch_dim_idx=1)
        outs = [None] * T
        for t in steps:
            xt = inputs[t] if self.time_major else inputs[:, t]
            y, new_states = self.cell(xt, states, **kwargs)
            if sequence_length is not None:
                # padded steps: keep prior state, emit zeros (reference
                # rnn.py mask_fn semantics)
                mask = (sequence_length > t).astype(y.dtype).unsqueeze(-1)
                y = y * mask
                states = map_states(
                    lambda n, o: n * mask + o * (1.0 - mask),
                    new_states, states)
            else:
                states = new_states
            outs[t] = y
        outputs = stack(outs, axis=time_axis)
        return outputs, states


class BiRNN(Layer):
    """Reference rnn.py BiRNN: forward + backward cells, concat outputs."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            fw0 = bw0 = None
        else:
            fw0, bw0 = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, fw0, sequence_length, **kwargs)
        out_bw, st_bw = self.rnn_bw(inputs, bw0, sequence_length, **kwargs)
        from ..ops.manipulation import concat
        return concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (optionally bidirectional) stack over the fused scan
    ops. Parameter names follow the reference convention
    (weight_ih_l{k}[_reverse], ...) so state_dicts line up."""

    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None, mode=None):
        super().__init__()
        if mode is not None:
            self.MODE = mode    # instance override (SimpleRNN relu)
        if direction in ("forward",):
            self.num_directions = 1
        elif direction in ("bidirect", "bidirectional"):
            self.num_directions = 2
        else:
            raise ValueError(f"unknown direction {direction!r}")
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout

        if self.MODE == "LSTM":
            g = 4
        elif self.MODE == "GRU":
            g = 3
        else:
            g = 1
        from .initializer import Uniform
        std = 1.0 / np.sqrt(hidden_size)
        init = Uniform(-std, std)
        for layer in range(num_layers):
            for d in range(self.num_directions):
                isz = input_size if layer == 0 else \
                    hidden_size * self.num_directions
                sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
                for pname, shape, attr, is_bias in (
                        (f"weight_ih_{sfx}", [g * hidden_size, isz],
                         weight_ih_attr, False),
                        (f"weight_hh_{sfx}", [g * hidden_size, hidden_size],
                         weight_hh_attr, False),
                        (f"bias_ih_{sfx}", [g * hidden_size], bias_ih_attr,
                         True),
                        (f"bias_hh_{sfx}", [g * hidden_size], bias_hh_attr,
                         True)):
                    p = self.create_parameter(shape, attr=attr,
                                              is_bias=is_bias,
                                              default_initializer=init)
                    setattr(self, pname, p)

    def _scan_one(self, x, h0, params, seq_len, reverse):
        w_ih, w_hh, b_ih, b_hh = params
        if self.MODE == "LSTM":
            h0, c0 = h0
            ys, h, c = lstm_scan(x, h0, c0, w_ih, w_hh, b_ih, b_hh,
                                 seq_len=seq_len, reverse=reverse)
            return ys, (h, c)
        if self.MODE == "GRU":
            ys, h = gru_scan(x, h0, w_ih, w_hh, b_ih, b_hh,
                             seq_len=seq_len, reverse=reverse)
            return ys, h
        ys, h = simple_rnn_scan(
            x, h0, w_ih, w_hh, b_ih, b_hh, seq_len=seq_len, reverse=reverse,
            activation="tanh" if self.MODE == "RNN_TANH" else "relu")
        return ys, h

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..ops.manipulation import transpose as _transpose
        x = inputs
        if not self.time_major:
            x = _transpose(x, [1, 0, 2])        # -> [T, B, I]
        T, B = x.shape[0], x.shape[1]
        L, D, H = self.num_layers, self.num_directions, self.hidden_size

        is_lstm = self.MODE == "LSTM"
        if initial_states is None:
            z = Tensor(jnp.zeros((L * D, B, H), self.weight_ih_l0._data.dtype))
            initial_states = (z, z) if is_lstm else z

        seq_len = sequence_length
        final_h, final_c = [], []
        out = x
        for layer in range(L):
            layer_outs = []
            for d in range(D):
                sfx = f"l{layer}" + ("_reverse" if d == 1 else "")
                params = tuple(getattr(self, f"{n}_{sfx}") for n in
                               ("weight_ih", "weight_hh", "bias_ih",
                                "bias_hh"))
                idx = layer * D + d
                if is_lstm:
                    h0 = (initial_states[0][idx], initial_states[1][idx])
                else:
                    h0 = initial_states[idx]
                ys, st = self._scan_one(out, h0, params, seq_len, d == 1)
                layer_outs.append(ys)
                if is_lstm:
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            if D == 2:
                from ..ops.manipulation import concat
                out = concat(layer_outs, axis=-1)
            else:
                out = layer_outs[0]
            if self.dropout > 0.0 and layer < L - 1 and self.training:
                out = F.dropout(out, p=self.dropout, training=True)
        from ..ops.manipulation import stack
        h_stack = stack(final_h, axis=0)
        if not self.time_major:
            out = _transpose(out, [1, 0, 2])
        if is_lstm:
            return out, (h_stack, stack(final_c, axis=0))
        return out, h_stack


class SimpleRNN(_RNNBase):
    MODE = "RNN_TANH"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__(
            input_size, hidden_size, num_layers, direction, time_major,
            dropout, mode="RNN_RELU" if activation == "relu" else "RNN_TANH",
            **kwargs)


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"
