"""MoE layer (reference: incubate/distributed/models/moe/moe_layer.py
MoELayer:119 — here over the GShard dense-dispatch core in
distributed/moe.py, expert weights stored stacked [E, ...] so expert
parallelism is a Shard(0) placement, not a code path)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .layer import Layer
from ..ops.registry import op
from ..distributed.moe import moe_dispatch_combine

__all__ = ["MoELayer"]


@op(name="moe_forward")
def _moe_forward(x, gate_w, w1, b1, w2, b2, top_k=2, capacity_factor=1.25,
                 mesh=None, ep_axis="ep", train=True, noise_key=None):
    s0 = x.shape
    flat = x.reshape(-1, s0[-1])
    y, aux = moe_dispatch_combine(
        flat, gate_w, w1, b1, w2, b2, top_k=top_k,
        capacity_factor=capacity_factor, mesh=mesh, ep_axis=ep_axis,
        train=train, noise_key=noise_key)
    return y.reshape(s0), aux


class MoELayer(Layer):
    """Top-k routed FFN with static capacity.

    moe = MoELayer(d_model=512, d_hidden=1024, num_experts=8, top_k=2)
    y = moe(x)           # x: [B, S, d_model]
    moe.aux_loss         # load-balance loss of the last forward
    """

    def __init__(self, d_model, d_hidden, num_experts, top_k=2,
                 capacity_factor=1.25, gate=None, mesh=None, ep_axis="ep",
                 name=None):
        super().__init__()
        self.d_model, self.d_hidden = d_model, d_hidden
        self.num_experts, self.top_k = num_experts, top_k
        self.capacity_factor = capacity_factor
        self.mesh, self.ep_axis = mesh, ep_axis
        e = num_experts
        self.gate_weight = self.create_parameter([d_model, e])
        self.w1 = self.create_parameter([e, d_model, d_hidden])
        self.b1 = self.create_parameter([e, d_hidden], is_bias=True)
        self.w2 = self.create_parameter([e, d_hidden, d_model])
        self.b2 = self.create_parameter([e, d_model], is_bias=True)
        self.aux_loss = None

    def forward(self, x):
        from ..framework import random as _random
        noise_key = _random.split_key() if self.training else None
        y, aux = _moe_forward(
            x, self.gate_weight, self.w1, self.b1, self.w2, self.b2,
            top_k=self.top_k, capacity_factor=self.capacity_factor,
            mesh=self.mesh.jax_mesh if hasattr(self.mesh, "jax_mesh")
            else self.mesh,
            ep_axis=self.ep_axis, train=self.training,
            noise_key=noise_key)
        self.aux_loss = aux
        return y
