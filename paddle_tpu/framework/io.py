"""paddle.save / paddle.load.

Reference: python/paddle/framework/io.py:773,1020 — pickle-based state_dict
I/O.  Tensors are serialized as numpy arrays inside the pickle (protocol
compatible enough for round-tripping within this framework); `paddle.load`
rebuilds Tensors on the default device.
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .tensor import Tensor

__all__ = ["save", "load"]


class _TensorPayload:
    """Pickle stand-in for a Tensor."""

    def __init__(self, array: np.ndarray, stop_gradient: bool, name: str):
        self.array = array
        self.stop_gradient = stop_gradient
        self.name = name


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(obj.numpy(), obj.stop_gradient, obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Tensor(obj.array, stop_gradient=obj.stop_gradient)
        t.name = obj.name
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)
