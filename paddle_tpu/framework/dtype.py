"""Dtype system.

Paddle exposes dtypes as ``paddle.float32``-style singletons backed by a
``VarDesc.VarType`` enum (reference: paddle/phi/common/data_type.h,
python/paddle/framework/dtype.py).  Here each dtype is a thin singleton over a
numpy dtype, so it converts transparently to jax/numpy while printing as
``paddle.float32``.
"""
from __future__ import annotations

import numpy as np

# NB: "dtype" (the coercion function) is deliberately NOT in __all__ so that
# `from .dtype import *` in the package __init__ doesn't shadow this module's
# attribute on the package (framework.dtype must stay the module).
__all__ = [
    "DType", "convert_dtype", "to_np_dtype",
    "bool_", "uint8", "int8", "int16", "int32", "int64",
    "float16", "bfloat16", "float32", "float64",
    "complex64", "complex128",
]

import ml_dtypes as _ml_dtypes


class DType:
    """A framework dtype: named singleton over a numpy dtype."""

    _registry: dict[str, "DType"] = {}

    __slots__ = ("name", "np_dtype")

    def __init__(self, name: str, np_dtype):
        self.name = name
        self.np_dtype = np.dtype(np_dtype)
        DType._registry[name] = self

    def __repr__(self):
        return f"paddle.{self.name}"

    # numpy/jax interop: np.dtype(paddle.float32) works.
    @property
    def itemsize(self):
        return self.np_dtype.itemsize

    def __eq__(self, other):
        if isinstance(other, DType):
            return self.name == other.name
        try:
            return self.np_dtype == np.dtype(to_np_dtype(other))
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.name)

    @property
    def is_floating_point(self):
        return np.issubdtype(self.np_dtype, np.floating) or self.name == "bfloat16"

    @property
    def is_integer(self):
        return np.issubdtype(self.np_dtype, np.integer)

    @property
    def is_complex(self):
        return np.issubdtype(self.np_dtype, np.complexfloating)


bool_ = DType("bool", np.bool_)
uint8 = DType("uint8", np.uint8)
int8 = DType("int8", np.int8)
int16 = DType("int16", np.int16)
int32 = DType("int32", np.int32)
int64 = DType("int64", np.int64)
float16 = DType("float16", np.float16)
bfloat16 = DType("bfloat16", _ml_dtypes.bfloat16)
float32 = DType("float32", np.float32)
float64 = DType("float64", np.float64)
complex64 = DType("complex64", np.complex64)
complex128 = DType("complex128", np.complex128)

float8_e4m3fn = DType("float8_e4m3fn", _ml_dtypes.float8_e4m3fn)
float8_e5m2 = DType("float8_e5m2", _ml_dtypes.float8_e5m2)

_NP_TO_DTYPE = {d.np_dtype: d for d in DType._registry.values()}


def dtype(x) -> DType:
    """Coerce anything dtype-like to a DType singleton."""
    if isinstance(x, DType):
        return x
    if isinstance(x, str):
        d = DType._registry.get(x)
        if d is not None:
            return d
    npd = np.dtype(x)
    d = _NP_TO_DTYPE.get(npd)
    if d is None:
        raise TypeError(f"unsupported dtype: {x!r}")
    return d


def to_np_dtype(x):
    """Convert dtype-like (DType, str, np/jnp dtype) to numpy dtype."""
    if isinstance(x, DType):
        return x.np_dtype
    if isinstance(x, str) and x in DType._registry:
        return DType._registry[x].np_dtype
    return np.dtype(x)


def convert_dtype(x) -> str:
    """Paddle-compat: return canonical dtype name string."""
    return dtype(x).name
