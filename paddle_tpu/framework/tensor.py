"""The framework Tensor: a Paddle-shaped handle over a jax.Array.

Reference: paddle::Tensor (paddle/phi/api/include/tensor.h:82) over
phi::DenseTensor (paddle/phi/core/dense_tensor.h:37) with an AutogradMeta
slot (paddle/fluid/eager/autograd_meta.h).  Here the storage is a jax.Array
(or a jax tracer during `jit` tracing — every method stays traceable), the
autograd slot is a tape GradNode, and device/layout/distribution all live in
the underlying jax.Array's sharding.  Arrays are immutable; "in-place" APIs
rebind the handle, which is semantically equivalent for a single-threaded
dygraph program and keeps the functional core jit-compatible.

Aliasing policy (documented divergence — README "Compatibility policy"):
reference Paddle's reshape/view/slice results alias their base, so later
in-place mutation of the base shows through the view.  Here views are
value snapshots: after ``b = a.reshape(...)``, ``a[0] = 7`` rebinds ``a``
and ``b`` keeps the old values.  Re-derive views after mutating the base
when porting code that relies on write-through aliasing.
"""
from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

__all__ = ["Tensor", "to_tensor", "is_tensor"]


# ------------------------------------------------- strict view semantics
# The documented aliasing-policy divergence (README "Compatibility
# policy"): views are value snapshots here, not aliases.  With
# FLAGS_strict_view_semantics=1 the hazard becomes an ERROR instead of a
# silent divergence — mutating a tensor while a linked view/base is
# alive raises, pointing at the policy.  Near-zero overhead when off
# (one dict get per view-method call; no imports, no tracking).
import weakref as _weakref

from ..flags import FLAGS as _FLAGS


def _strict_views_on():
    return bool(_FLAGS.get("FLAGS_strict_view_semantics", False))


def _link_view(base, view):
    """Record the view relation so either side's in-place mutation can
    be flagged while the other is alive.  Views link to their ROOT base
    (chains like a.reshape(...)[1:3] stay linked to `a` even after the
    intermediate dies — transitive aliasing is what the reference
    shares storage across)."""
    if base is view:
        return view
    root = base
    # _views layout: (root_weakref, [peer_weakrefs]) — tensors that are
    # themselves views carry their root in slot 0 (None for true bases)
    if base._views is not None and base._views[0] is not None:
        rt = base._views[0]()
        if rt is not None:
            root = rt
    view._views = (_weakref.ref(root),
                   [] if view._views is None else view._views[1])
    if root._views is None:
        root._views = (None, [])
    root._views[1].append(_weakref.ref(view))
    view._views[1].append(_weakref.ref(root))
    return view


def _check_view_mutation(t):
    if t._views is None or not _strict_views_on():
        return
    if any(r() is not None for r in t._views[1]):
        raise RuntimeError(
            "FLAGS_strict_view_semantics: in-place mutation of a tensor "
            "with live views (or of a view whose base is alive). "
            "Reference Paddle aliases storage here; paddle_tpu views are "
            "value snapshots (README 'Compatibility policy') — re-derive "
            "the view after mutating, or drop the strict flag to accept "
            "snapshot semantics.")


def _default_dtype_for(data):
    """Paddle default dtype rules: python/np float64 data → float32 (the
    framework default float), ints stay int64, bools stay bool."""
    if isinstance(data, bool):
        return np.bool_
    if isinstance(data, int):
        return np.int64
    if isinstance(data, float):
        return np.float32
    arr = data if isinstance(data, np.ndarray) else None
    if arr is None and isinstance(data, (list, tuple)):
        arr = np.asarray(data)
    if arr is not None and arr.dtype == np.float64:
        return np.float32
    return None


class Tensor:
    """Eager tensor handle (paddle.Tensor API shape)."""

    __slots__ = ("_data", "stop_gradient", "_grad", "_grad_node", "_out_index",
                 "name", "persistable", "trainable", "_views", "__weakref__")

    _next_name_id = 0

    def __init__(self, data: Any, dtype=None, place=None, stop_gradient=True,
                 name=None):
        if isinstance(data, Tensor):
            data = data._data
        if dtype is not None:
            npd = dtypes.to_np_dtype(dtype)
            if isinstance(data, (jax.Array, jax.core.Tracer)):
                data = data.astype(npd) if data.dtype != npd else data
            else:
                data = jnp.asarray(data, dtype=npd)
        elif not isinstance(data, (jax.Array, jax.core.Tracer)):
            d = _default_dtype_for(data)
            data = jnp.asarray(data, dtype=d)
        self._data = data
        self.stop_gradient = stop_gradient
        self._grad = None           # jax array or None
        self._grad_node = None      # tape.GradNode
        self._out_index = 0
        self._views = None          # strict-view-mode link list
        self.persistable = False
        self.trainable = not stop_gradient
        if name is None:
            name = f"generated_tensor_{Tensor._next_name_id}"
            Tensor._next_name_id += 1
        self.name = name

    # ------------------------------------------------------------- metadata
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def dtype(self) -> dtypes.DType:
        return dtypes.dtype(self._data.dtype)

    @property
    def place(self):
        try:
            return next(iter(self._data.devices()))
        except Exception:
            return jax.devices()[0]

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def grad(self):
        from .selected_rows import RowSparseGrad
        if isinstance(self._grad, RowSparseGrad):
            return self._grad  # row-sparse grads surface as-is
        if self._grad is None:
            return None
        return Tensor(self._grad, stop_gradient=True)

    @grad.setter
    def grad(self, value):
        from .selected_rows import RowSparseGrad
        if value is None:
            self._grad = None
        elif isinstance(value, RowSparseGrad):
            self._grad = value
        else:
            self._grad = value._data if isinstance(value, Tensor) else jnp.asarray(value)

    def clear_grad(self, set_to_zero=False):
        from .selected_rows import RowSparseGrad
        if set_to_zero and self._grad is not None:
            if isinstance(self._grad, RowSparseGrad):
                # keep the row-sparse form: never materialize [V, D]
                g = self._grad
                self._grad = RowSparseGrad(
                    g.rows, jnp.zeros_like(g.values), g.dense_shape)
            else:
                self._grad = jnp.zeros_like(self._grad)
        else:
            self._grad = None

    clear_gradient = clear_grad

    # ------------------------------------------------------------ conversion
    def numpy(self):
        return np.asarray(self._data)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._data

    def __float__(self):
        return float(self.item())

    def __int__(self):
        return int(self.item())

    def __bool__(self):
        return bool(self._data)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __index__(self):
        return int(self.item())

    def __hash__(self):
        return id(self)

    # ------------------------------------------------------------- autograd
    def backward(self, grad_tensor=None, retain_graph=False):
        from ..autograd import tape
        tape.backward([self], [grad_tensor], retain_graph=retain_graph)

    def detach(self):
        t = Tensor(self._data, stop_gradient=True)
        t.name = self.name + ".detach"
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def register_hook(self, hook):
        # Gradient hooks: wrap the current node's vjp. Minimal but functional.
        from ..autograd import tape as _tape
        node = self._grad_node
        if node is None:
            raise RuntimeError("register_hook on a leaf tensor requires a grad node")
        idx = self._out_index
        orig = node.vjp_fn

        def hooked(flat_cots):
            cots = list(flat_cots)
            g = hook(Tensor(cots[idx], stop_gradient=True))
            if g is not None:
                cots[idx] = g._data if isinstance(g, Tensor) else g
            return orig(tuple(cots))

        node.vjp_fn = hooked
        node.raw_vjp = None   # python hook: opt this graph out of the
        return hook           # fused-backward replay (tape.py)

    # ----------------------------------------------------------- rebinding
    def _rebind_(self, other: "Tensor"):
        """In-place semantics: point this handle at another result."""
        _check_view_mutation(self)
        self._data = other._data
        self._grad_node = other._grad_node
        self._out_index = other._out_index
        self.stop_gradient = self.stop_gradient and other.stop_gradient
        return self

    def copy_(self, other, blocking=True):
        _check_view_mutation(self)
        other = to_tensor(other)
        self._data = other._data.astype(self._data.dtype)
        return self

    def set_value(self, value):
        _check_view_mutation(self)
        value = to_tensor(value)
        self._data = jnp.broadcast_to(
            value._data.astype(self._data.dtype), self._data.shape)
        return self

    # ------------------------------------------------------------- printing
    def __repr__(self):
        prefix = "Tensor(shape={}, dtype={}, stop_gradient={},\n       ".format(
            self.shape, self.dtype.name, self.stop_gradient)
        try:
            body = np.array2string(self.numpy(), separator=", ", prefix="       ")
        except Exception:
            body = f"<traced {self._data}>"
        return prefix + body + ")"

    __str__ = __repr__

    # Device movement: all no-ops / placements on TPU runtime.
    def cpu(self):
        return Tensor(jax.device_get(self._data), stop_gradient=self.stop_gradient)

    def cuda(self, device_id=None, blocking=True):
        return self

    def to(self, *args, **kwargs):
        # to(dtype) / to(device) / to(device, dtype)
        dt = kwargs.get("dtype")
        for a in args:
            if isinstance(a, (str, dtypes.DType)) and not isinstance(a, bool):
                try:
                    dt = dtypes.dtype(a)
                except TypeError:
                    continue
        if dt is not None:
            return self.astype(dt)
        return self

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    @property
    def T(self):
        from .. import ops
        return ops.linalg.transpose(self, list(range(self.ndim))[::-1])

    @property
    def mT(self):
        from .. import ops
        perm = list(range(self.ndim))
        perm[-2], perm[-1] = perm[-1], perm[-2]
        return ops.linalg.transpose(self, perm)


def is_tensor(x):
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor."""
    if isinstance(data, Tensor):
        if dtype is not None and dtypes.dtype(dtype) != data.dtype:
            data = data.astype(dtype)
        t = Tensor(data._data, stop_gradient=stop_gradient)
        t._grad_node = data._grad_node if not stop_gradient else None
        t._out_index = data._out_index
        return t
    return Tensor(data, dtype=dtype, stop_gradient=stop_gradient)
