"""RNG state.

Reference: phi::Generator (paddle/phi/core/generator.h) — a per-device
stateful generator seeded by `paddle.seed`.  On TPU randomness is functional
(threaded PRNG keys), so the "generator" holds a key and splits it per call.
For compiled training steps, a traced key can be pushed with
:func:`trace_key_guard` — inside that scope every split derives from the
traced key via `fold_in` with a trace-time counter, so each call site gets an
independent stream and the whole step stays a pure function of the key.
"""
from __future__ import annotations

import contextlib
import threading

import jax

__all__ = ["Generator", "default_generator", "seed", "get_rng_state",
           "set_rng_state", "split_key", "trace_key_guard"]


class Generator:
    def __init__(self, seed_: int = 0):
        # key creation is deferred: materializing it at import time would
        # initialize the XLA backend before jax.distributed.initialize
        # can run (breaks multi-process startup)
        self._key = None
        self._seed = seed_

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def manual_seed(self, seed_: int):
        # stays deferred too: paddle.seed() is often the first line of a
        # worker script, before init_parallel_env
        self._key = None
        self._seed = seed_
        return self

    seed = manual_seed

    def initial_seed(self):
        return self._seed

    def get_state(self):
        self._ensure()
        return self._key

    def set_state(self, state):
        self._key = state

    def split(self):
        self._ensure()
        self._key, sub = jax.random.split(self._key)
        return sub


class _TraceState(threading.local):
    def __init__(self):
        self.stack = []  # list of [key, counter]


_trace = _TraceState()
default_generator = Generator(0)


def seed(s: int):
    """paddle.seed."""
    default_generator.manual_seed(int(s))
    return default_generator


def get_rng_state():
    return default_generator.get_state()


def set_rng_state(state):
    default_generator.set_state(state)


@contextlib.contextmanager
def trace_key_guard(key):
    """Make split_key() derive from ``key`` (possibly traced) in this scope."""
    _trace.stack.append([key, 0])
    try:
        yield
    finally:
        _trace.stack.pop()


class _WatchState(threading.local):
    def __init__(self):
        self.active = False
        self.used = False


_watch = _WatchState()


class _WatchResult:
    __slots__ = ("used",)

    def __init__(self):
        self.used = False


@contextlib.contextmanager
def watch_rng_use():
    """Record whether split_key() fires inside the scope.  Used by the
    eager dispatch cache (ops/registry.py): an op body that consumes
    eager randomness at trace time would bake the key into the cached
    executable and replay the same stream forever — such ops must stay
    on the uncached path."""
    prev = (_watch.active, _watch.used)
    _watch.active, _watch.used = True, False
    res = _WatchResult()
    try:
        yield res
    finally:
        res.used = _watch.used
        _watch.active, _watch.used = prev


def split_key():
    """One fresh PRNG key for a random op."""
    if _watch.active:
        _watch.used = True
    if _trace.stack:
        entry = _trace.stack[-1]
        entry[1] += 1
        return jax.random.fold_in(entry[0], entry[1])
    return default_generator.split()
