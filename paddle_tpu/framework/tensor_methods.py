"""Attach tensor methods + operator dunders to Tensor.

Reference: python/paddle/base/dygraph/tensor_patch_methods.py +
math_op_patch.py monkey-patch methods onto the C++ eager.Tensor; same idea
here over the op registry.  Called once from package __init__.
"""
from __future__ import annotations

from .tensor import Tensor
from .. import ops
from ..ops import math as m, reduction as r, manipulation as mp, \
    creation as c, linalg as lg, comparison as cmp, indexing as ix, \
    math_extra as mx
from .random import split_key as _split_key

# method name -> op callable taking (self, ...)
_METHODS = dict(
    # math
    add=m.add, subtract=m.subtract, multiply=m.multiply, divide=m.divide,
    floor_divide=m.floor_divide, remainder=m.remainder, mod=m.remainder,
    pow=m.pow, matmul=m.matmul, scale=m.scale, neg=m.neg, abs=m.abs,
    exp=m.exp, expm1=m.expm1, log=m.log, log2=m.log2, log10=m.log10,
    log1p=m.log1p, sqrt=m.sqrt, rsqrt=m.rsqrt, square=m.square,
    sin=m.sin, cos=m.cos, tan=m.tan, asin=m.asin, acos=m.acos, atan=m.atan,
    sinh=m.sinh, cosh=m.cosh, tanh=m.tanh, asinh=m.asinh, acosh=m.acosh,
    atanh=m.atanh, erf=m.erf, erfinv=m.erfinv, floor=m.floor, ceil=m.ceil,
    round=m.round, trunc=m.trunc, sign=m.sign, reciprocal=m.reciprocal,
    sigmoid=m.sigmoid, digamma=m.digamma, lgamma=m.lgamma, frac=m.frac,
    conj=m.conj, real=m.real, imag=m.imag, angle=m.angle,
    clip=m.clip, maximum=m.maximum, minimum=m.minimum, fmax=m.fmax,
    fmin=m.fmin, atan2=m.atan2, lerp=m.lerp, logit=m.logit,
    isnan=m.isnan, isinf=m.isinf, isfinite=m.isfinite,
    nan_to_num=m.nan_to_num, cumsum=m.cumsum, cumprod=m.cumprod,
    cummax=m.cummax, cummin=m.cummin, logcumsumexp=m.logcumsumexp,
    addmm=m.addmm, inner=m.inner, outer=m.outer, heaviside=m.heaviside,
    gcd=m.gcd, lcm=m.lcm, diff=m.diff, trace=m.trace, kron=m.kron,
    cross=m.cross, dot=m.dot, hypot=m.hypot,
    # reduction
    sum=r.sum_, mean=r.mean, max=r.max_, min=r.min_, amax=r.amax,
    amin=r.amin, prod=r.prod, all=r.all_, any=r.any_, var=r.var, std=r.std,
    nansum=r.nansum, nanmean=r.nanmean, count_nonzero=r.count_nonzero,
    logsumexp=r.logsumexp, argmax=r.argmax, argmin=r.argmin, median=r.median,
    nanmedian=r.nanmedian, quantile=r.quantile, kthvalue=r.kthvalue,
    mode=r.mode,
    # manipulation
    reshape=mp.reshape, transpose=mp.transpose, squeeze=mp.squeeze,
    unsqueeze=mp.unsqueeze, flatten=mp.flatten, tile=mp.tile,
    expand=mp.expand, expand_as=mp.expand_as, broadcast_to=mp.broadcast_to,
    gather=mp.gather, gather_nd=mp.gather_nd, scatter=mp.scatter,
    scatter_nd_add=mp.scatter_nd_add, index_select=mp.index_select,
    index_add=mp.index_add, index_put=mp.index_put,
    index_sample=mp.index_sample,
    take_along_axis=mp.take_along_axis, put_along_axis=mp.put_along_axis,
    flip=mp.flip, roll=mp.roll, rot90=mp.rot90, where=mp.where,
    nonzero=mp.nonzero, masked_select=mp.masked_select,
    masked_fill=mp.masked_fill, topk=mp.topk, sort=mp.sort,
    argsort=mp.argsort, unique=mp.unique,
    unique_consecutive=mp.unique_consecutive, tril=mp.tril, triu=mp.triu,
    diag=mp.diag, diagonal=mp.diagonal, diag_embed=mp.diag_embed,
    cast=mp.cast, pad=mp.pad, repeat_interleave=mp.repeat_interleave,
    moveaxis=mp.moveaxis, swapaxes=mp.swapaxes, as_strided=mp.as_strided,
    split=mp.split, chunk=mp.chunk, unstack=mp.unstack, unfold=mp.unfold,
    numel=mp.numel, increment=mp.increment, bincount=mp.bincount,
    histogram=mp.histogram, searchsorted=mp.searchsorted,
    bucketize=mp.bucketize, unbind=mp.unstack,
    # linalg
    mm=lg.mm, bmm=lg.bmm, mv=lg.mv, t=lg.t, norm=lg.norm, dist=lg.dist,
    cholesky=lg.cholesky, cholesky_solve=lg.cholesky_solve, qr=lg.qr,
    svd=lg.svd, inv=lg.inv, pinv=lg.pinv, det=lg.det, slogdet=lg.slogdet,
    solve=lg.solve, triangular_solve=lg.triangular_solve, lu=lg.lu,
    eig=lg.eig, eigvals=lg.eigvals, matrix_power=lg.matrix_power,
    matrix_rank=lg.matrix_rank, cond=lg.cond, lstsq=lg.lstsq,
    bitwise_and=lg.bitwise_and, bitwise_or=lg.bitwise_or,
    bitwise_xor=lg.bitwise_xor, bitwise_not=lg.bitwise_not,
    bitwise_left_shift=lg.bitwise_left_shift,
    bitwise_right_shift=lg.bitwise_right_shift,
    # comparison
    equal=cmp.equal, not_equal=cmp.not_equal, greater_than=cmp.greater_than,
    greater_equal=cmp.greater_equal, less_than=cmp.less_than,
    less_equal=cmp.less_equal, equal_all=cmp.equal_all,
    allclose=cmp.allclose, isclose=cmp.isclose,
    logical_and=cmp.logical_and, logical_or=cmp.logical_or,
    logical_xor=cmp.logical_xor, logical_not=cmp.logical_not,
    # creation-likes
    zeros_like=c.zeros_like, ones_like=c.ones_like, full_like=c.full_like,
    clone=c.clone, bernoulli=c.bernoulli, multinomial=c.multinomial,
    normal_=None, exponential_=None,  # filled below
    # surface part 2 (ops/math_extra.py)
    logaddexp=mx.logaddexp, copysign=mx.copysign, ldexp=mx.ldexp,
    nextafter=mx.nextafter, signbit=mx.signbit, sinc=mx.sinc,
    frexp=mx.frexp, gammaln=mx.gammaln, gammainc=mx.gammainc,
    gammaincc=mx.gammaincc, multigammaln=mx.multigammaln, i0=m.i0,
    i0e=mx.i0e, i1=mx.i1, i1e=mx.i1e, sgn=mx.sgn, isin=mx.isin,
    take=mx.take, trapezoid=mx.trapezoid,
    cumulative_trapezoid=mx.cumulative_trapezoid, vander=mx.vander,
    renorm=mx.renorm, nanquantile=mx.nanquantile, floor_mod=mx.floor_mod,
    reduce_as=mx.reduce_as, tensor_split=mx.tensor_split,
    hsplit=mx.hsplit, vsplit=mx.vsplit, dsplit=mx.dsplit,
    diagonal_scatter=mx.diagonal_scatter, select_scatter=mx.select_scatter,
    slice_scatter=mx.slice_scatter, masked_scatter=mx.masked_scatter,
    index_fill=mx.index_fill, reverse=mx.reverse, unflatten=mx.unflatten,
    view_as=mx.view_as, as_complex=mx.as_complex, as_real=mx.as_real,
    isneginf=mx.isneginf, isposinf=mx.isposinf, isreal=mx.isreal,
    cdist=mx.cdist, polygamma=m.polygamma,
)

# in-place variants: run op then rebind handle
_INPLACE = [
    "add", "subtract", "multiply", "divide", "remainder", "floor_divide",
    "pow", "scale", "clip", "exp", "log", "sqrt", "rsqrt", "square", "abs",
    "neg", "floor", "ceil", "round", "trunc", "reciprocal", "sigmoid",
    "tanh", "erfinv", "cast", "reshape", "squeeze", "unsqueeze", "flatten",
    "transpose", "tril", "triu", "lerp", "masked_fill", "scatter",
    "index_add", "index_put", "put_along_axis", "nan_to_num", "where",
    # surface part 2
    "sin", "cos", "tan", "asin", "acos", "atan", "sinh", "cosh", "asinh",
    "acosh", "atanh", "erf", "expm1", "log2", "log10", "log1p", "digamma",
    "lgamma", "gammaln", "gammainc", "gammaincc", "multigammaln",
    "polygamma", "gcd", "lcm", "hypot", "ldexp", "copysign", "i0", "frac",
    "cumsum", "cumprod", "logit", "sinc", "renorm", "index_fill",
    "masked_scatter", "logical_and", "logical_or", "logical_xor",
    "logical_not", "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not",
    "bitwise_left_shift", "bitwise_right_shift", "equal", "not_equal",
    "greater_than", "greater_equal", "less_than", "less_equal", "mod",
    "floor_mod", "t", "addmm",
]


def _make_method(fn):
    def method(self, *args, **kwargs):
        return fn(self, *args, **kwargs)
    method.__name__ = fn.__name__
    return method


def _make_inplace(fn):
    def method(self, *args, **kwargs):
        out = fn(self, *args, **kwargs)
        return self._rebind_(out)
    method.__name__ = fn.__name__ + "_"
    return method


def _patch():
    for name, fn in _METHODS.items():
        if fn is None:
            continue
        setattr(Tensor, name, _make_method(fn))
    for name in _INPLACE:
        fn = _METHODS.get(name)
        if fn is not None:
            setattr(Tensor, name + "_", _make_inplace(fn))

    def astype(self, dtype):
        return mp.cast(self, dtype)
    Tensor.astype = astype
    Tensor.type_as = lambda self, other: mp.cast(self, other.dtype)

    def normal_(self, mean=0.0, std=1.0):
        out = c.gaussian(self.shape, mean=mean, std=std, dtype=self.dtype)
        return self._rebind_(out.astype(self.dtype))
    Tensor.normal_ = normal_

    def uniform_(self, min=-1.0, max=1.0, seed=0):
        out = c.uniform(self.shape, dtype=self.dtype, min=min, max=max, seed=seed)
        return self._rebind_(out)
    Tensor.uniform_ = uniform_

    def zero_(self):
        return self._rebind_(c.zeros_like(self))
    Tensor.zero_ = zero_

    def fill_(self, value):
        return self._rebind_(c.full_like(self, value))
    Tensor.fill_ = fill_

    def exponential__(self, lam=1.0):
        return self._rebind_(c.exponential_(self, lam))
    Tensor.exponential_ = exponential__

    def bernoulli_(self, p=0.5):
        import jax
        self._data = jax.random.bernoulli(
            _split_key(), p, tuple(self.shape)).astype(self._data.dtype)
        self._grad_node = None
        self._out_index = None
        return self
    Tensor.bernoulli_ = bernoulli_

    def cauchy_(self, loc=0, scale=1):
        import jax, jax.numpy as jnp
        u = jax.random.uniform(_split_key(), tuple(self.shape))
        import math as _m
        self._data = (loc + scale * jnp.tan(_m.pi * (u - 0.5))).astype(
            self._data.dtype)
        self._grad_node = None
        self._out_index = None
        return self
    Tensor.cauchy_ = cauchy_

    def geometric_(self, probs):
        import jax, jax.numpy as jnp
        u = jax.random.uniform(_split_key(), tuple(self.shape),
                               minval=1e-7, maxval=1.0)
        self._data = jnp.ceil(
            jnp.log(u) / jnp.log1p(-probs)).astype(self._data.dtype)
        self._grad_node = None
        self._out_index = None
        return self
    Tensor.geometric_ = geometric_

    def log_normal_(self, mean=1.0, std=2.0):
        import jax, jax.numpy as jnp
        eps = jax.random.normal(_split_key(), tuple(self.shape))
        self._data = jnp.exp(mean + std * eps).astype(self._data.dtype)
        self._grad_node = None
        self._out_index = None
        return self
    Tensor.log_normal_ = log_normal_

    def tolist(self):
        import numpy as _np
        return _np.asarray(self._data).tolist()
    Tensor.tolist = tolist

    Tensor.is_complex = mx.is_complex
    Tensor.is_floating_point = mx.is_floating_point
    Tensor.is_integer = mx.is_integer

    # ---------------- operator dunders ----------------
    Tensor.__add__ = lambda s, o: m.add(s, o)
    Tensor.__radd__ = lambda s, o: m.add(s, o)
    Tensor.__sub__ = lambda s, o: m.subtract(s, o)
    Tensor.__rsub__ = lambda s, o: m.subtract(o, s)
    Tensor.__mul__ = lambda s, o: m.multiply(s, o)
    Tensor.__rmul__ = lambda s, o: m.multiply(s, o)
    Tensor.__truediv__ = lambda s, o: m.divide(s, o)
    Tensor.__rtruediv__ = lambda s, o: m.divide(o, s)
    Tensor.__floordiv__ = lambda s, o: m.floor_divide(s, o)
    Tensor.__rfloordiv__ = lambda s, o: m.floor_divide(o, s)
    Tensor.__mod__ = lambda s, o: m.remainder(s, o)
    Tensor.__rmod__ = lambda s, o: m.remainder(o, s)
    Tensor.__pow__ = lambda s, o: m.pow(s, o)
    Tensor.__rpow__ = lambda s, o: m.pow(o, s)
    Tensor.__matmul__ = lambda s, o: m.matmul(s, o)
    Tensor.__rmatmul__ = lambda s, o: m.matmul(o, s)
    Tensor.__neg__ = lambda s: m.neg(s)
    Tensor.__abs__ = lambda s: m.abs(s)
    Tensor.__invert__ = lambda s: cmp.logical_not(s) \
        if s.dtype.name == "bool" else lg.bitwise_not(s)
    Tensor.__and__ = lambda s, o: cmp.logical_and(s, o) \
        if s.dtype.name == "bool" else lg.bitwise_and(s, o)
    Tensor.__or__ = lambda s, o: cmp.logical_or(s, o) \
        if s.dtype.name == "bool" else lg.bitwise_or(s, o)
    Tensor.__xor__ = lambda s, o: cmp.logical_xor(s, o) \
        if s.dtype.name == "bool" else lg.bitwise_xor(s, o)
    Tensor.__eq__ = lambda s, o: cmp.equal(s, o)
    Tensor.__ne__ = lambda s, o: cmp.not_equal(s, o)
    Tensor.__lt__ = lambda s, o: cmp.less_than(s, o)
    Tensor.__le__ = lambda s, o: cmp.less_equal(s, o)
    Tensor.__gt__ = lambda s, o: cmp.greater_than(s, o)
    Tensor.__ge__ = lambda s, o: cmp.greater_equal(s, o)

    def _getitem(self, idx):
        return ix.getitem(self, idx)
    Tensor.__getitem__ = _getitem

    def _setitem(self, idx, value):
        self._rebind_(ix.setitem(self, idx, value))
    Tensor.__setitem__ = _setitem


_patch()


def _patch_surface2():
    """Tensor methods part 2 (reference tensor.prototype.pyi: dtype/layout
    introspection, sparse/dist predicates, strides)."""
    import numpy as _np
    import jax.numpy as _jnp

    Tensor.element_size = lambda self: self._data.dtype.itemsize
    Tensor.get_strides = lambda self: [
        int(_np.prod(self._data.shape[i + 1:]))
        for i in range(self._data.ndim)]
    Tensor.strides = property(lambda self: self.get_strides())
    Tensor.layout = property(lambda self: "NCHW")
    Tensor.offset = lambda self: 0
    Tensor.type = lambda self: "DenseTensor"
    Tensor.is_dense = lambda self: True
    Tensor.is_sparse = lambda self: False
    Tensor.is_sparse_coo = lambda self: False
    Tensor.is_sparse_csr = lambda self: False
    Tensor.is_selected_rows = lambda self: False
    Tensor.is_same_shape = lambda self, other: \
        list(self.shape) == list(other.shape)
    Tensor.get_tensor = lambda self: self
    Tensor.data = property(lambda self: self,
                           lambda self, v: self.copy_(v))

    def _is_dist(self):
        try:
            s = self._data.sharding
            return not s.is_fully_replicated
        except Exception:
            return False
    Tensor.is_dist = _is_dist

    def _placements(self):
        from ..distributed.auto_parallel.api import get_placements
        return get_placements(self)
    Tensor.placements = property(_placements)

    def _process_mesh(self):
        try:
            s = self._data.sharding
            return getattr(s, "mesh", None)
        except Exception:
            return None
    Tensor.process_mesh = property(_process_mesh)

    def _num_shard(self):
        try:
            return len(self._data.sharding.device_set)
        except Exception:
            return 1
    Tensor.num_shard = property(_num_shard)

    Tensor.grad_fn = property(lambda self: self._grad_node)
    Tensor._grad_ivar = lambda self: self.grad
    Tensor.grad_ = property(lambda self: self.grad)

    def _data_ptr(self):
        arr = _np.asarray(self._data)
        return arr.__array_interface__["data"][0]
    Tensor.data_ptr = _data_ptr

    def _sparse_only(name):
        def fn(self, *a, **k):
            raise ValueError(
                f"Tensor.{name}() is only defined for sparse/selected-rows "
                "tensors (paddle.sparse.SparseCooTensor / SparseCsrTensor)")
        fn.__name__ = name
        return fn

    for n in ("rows", "cols", "crows", "nnz", "get_selected_rows",
              "get_map_tensor", "set_vocab", "set_string_list"):
        setattr(Tensor, n, _sparse_only(n))


_patch_surface2()


def _patch_strict_views():
    """Wrap the view-creating methods so FLAGS_strict_view_semantics can
    link base<->view and turn write-through-aliasing hazards into errors
    (tensor.py _link_view / _check_view_mutation; README policy).  The
    flag gate runs BEFORE _link_view so the off-path costs one dict get."""
    from .tensor import _link_view, _strict_views_on

    for name in ("reshape", "view", "view_as", "squeeze", "unsqueeze",
                 "flatten", "detach"):
        orig = getattr(Tensor, name, None)
        if orig is None:
            continue

        def _mk(orig):
            def method(self, *args, **kwargs):
                out = orig(self, *args, **kwargs)
                if _strict_views_on() and isinstance(out, Tensor):
                    _link_view(self, out)
                return out
            method.__name__ = getattr(orig, "__name__", "view_method")
            return method

        setattr(Tensor, name, _mk(orig))

    orig_gi = Tensor.__getitem__

    def _getitem_linked(self, idx):
        out = orig_gi(self, idx)
        if _strict_views_on() and isinstance(out, Tensor):
            _link_view(self, out)
        return out

    Tensor.__getitem__ = _getitem_linked


_patch_strict_views()
