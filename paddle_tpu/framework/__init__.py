from . import dtype as dtype_module
from .dtype import *  # noqa: F401,F403
from .tensor import Tensor, to_tensor, is_tensor
from .random import seed, get_rng_state, set_rng_state, Generator, \
    default_generator, split_key, trace_key_guard
from .selected_rows import RowSparseGrad, merge_rows, rowsparse_all_gather

__all__ = ["Tensor", "to_tensor", "is_tensor", "seed", "get_rng_state",
           "set_rng_state", "Generator", "RowSparseGrad", "merge_rows",
           "rowsparse_all_gather"]
