"""Row-sparse gradients — the TPU-native SelectedRows.

The reference stores huge-vocab embedding gradients as a ``SelectedRows``
(row indices + value rows, ``paddle/phi/core/selected_rows.h``) with
dedicated kernels (``paddle/phi/kernels/selected_rows/``): the [V, D]
dense gradient is never materialized, and optimizers apply updates to the
touched rows only (``adam_kernel.cc`` lazy mode, sgd SelectedRows branch).

TPU formulation: a :class:`RowSparseGrad` pytree of ``rows [N] int32`` +
``values [N, D] `` with a *static* N (= number of lookups), so it is legal
under ``jit``.  Duplicate rows are allowed and mean "sum" (exactly
SelectedRows semantics).  ``merged()`` is the jit-safe analog of the
reference ``merge_selected_rows`` kernel: after it, rows are unique (dup
slots carry an out-of-range sentinel row and zero values, which every
consumer drops via scatter ``mode='drop'``).

The autograd tape carries RowSparseGrad cotangents natively: accumulation
is ``__add__`` (sparse+sparse = concat, sparse+dense = densify), leaves
hold it in ``Tensor._grad``, optimizers consume it row-wise (SGD always;
Adam/AdamW when ``lazy_mode=True``) and densify otherwise.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["RowSparseGrad", "merge_rows", "rowsparse_all_gather"]


@jax.tree_util.register_pytree_node_class
class RowSparseGrad:
    """rows: [N] int32 indices into dim 0; values: [N, *tail]; shape: dense."""

    def __init__(self, rows, values, dense_shape):
        self.rows = rows
        self.values = values
        self.dense_shape = tuple(int(s) for s in dense_shape)

    # ------------------------------------------------------------ pytree
    def tree_flatten(self):
        return (self.rows, self.values), self.dense_shape

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(children[0], children[1], aux)

    # ------------------------------------------------------- array-likes
    @property
    def shape(self):
        return self.dense_shape

    @property
    def dtype(self):
        return self.values.dtype

    def astype(self, dt):
        return RowSparseGrad(self.rows, self.values.astype(dt),
                             self.dense_shape)

    def __mul__(self, s):
        return RowSparseGrad(self.rows, self.values * s, self.dense_shape)

    __rmul__ = __mul__

    def __add__(self, other):
        if other is None:
            return self
        if isinstance(other, RowSparseGrad):
            if other.dense_shape != self.dense_shape:
                raise ValueError(
                    f"RowSparseGrad shape mismatch: {self.dense_shape} vs "
                    f"{other.dense_shape}")
            return RowSparseGrad(
                jnp.concatenate([self.rows, other.rows]),
                jnp.concatenate([self.values, other.values]),
                self.dense_shape)
        # dense on either side densifies (reference: sum over
        # SelectedRows+DenseTensor yields dense)
        return self.to_dense().astype(
            jnp.result_type(self.dtype, other.dtype)) + other

    __radd__ = __add__

    def __repr__(self):
        return (f"RowSparseGrad(rows={self.rows.shape}, "
                f"values={self.values.shape}, dense={self.dense_shape})")

    # ------------------------------------------------------------- kernels
    def to_dense(self):
        """Dense [V, D] equivalent (scatter-add; duplicate rows sum)."""
        buf = jnp.zeros(self.dense_shape, self.values.dtype)
        return buf.at[self.rows].add(self.values, mode="drop")

    def merged(self):
        """jit-safe merge_selected_rows: unique rows, dup slots zeroed.

        Sorts rows, segment-sums duplicate runs into the run's first slot,
        and marks the other slots with the out-of-range sentinel ``V`` so
        scatters with ``mode='drop'`` ignore them.  N is unchanged (static
        shapes under jit); consumers never index by sentinel rows.
        """
        v_sentinel = self.dense_shape[0]
        order = jnp.argsort(self.rows)
        r = self.rows[order]
        v = self.values[order]
        first = jnp.concatenate(
            [jnp.ones((1,), bool), r[1:] != r[:-1]]) if r.shape[0] else \
            jnp.ones((0,), bool)
        # run id per slot; segment-sum values into the run's first position
        run = jnp.cumsum(first.astype(jnp.int32)) - 1
        summed = jax.ops.segment_sum(v, run, num_segments=max(r.shape[0], 1))
        rows_out = jnp.where(first, r, v_sentinel) if r.shape[0] else r
        # each run's first slot keeps the run sum; dup slots zero
        vals_out = jnp.where(_bmask(first, v.ndim), summed[run],
                             0).astype(v.dtype)
        return RowSparseGrad(rows_out, vals_out, self.dense_shape)

    def _sq_norm(self):
        """Sum of squares of the DENSE equivalent (merges duplicates)."""
        m = self.merged()
        return jnp.sum(jnp.square(m.values.astype(jnp.float32)))

def _bmask(mask, ndim):
    return mask.reshape(mask.shape + (1,) * (ndim - 1))


def merge_rows(g: RowSparseGrad) -> RowSparseGrad:
    """Functional alias of :meth:`RowSparseGrad.merged` (reference
    ``merge_selected_rows`` op)."""
    return g.merged()


def rowsparse_all_gather(g: RowSparseGrad, axis_name: str) -> RowSparseGrad:
    """Data-parallel reduction of a row-sparse grad: concatenate every
    rank's (rows, values) — the SelectedRows analog of allreduce (the
    reference DP reducer allgathers SelectedRows rows/values rather than
    densifying, ``python/paddle/distributed/parallel.py`` sparse branch).

    Call inside ``shard_map``/``pmap`` with a bound ``axis_name``.  The
    result's N is world_size * N_local (static).
    """
    rows = jax.lax.all_gather(g.rows, axis_name, tiled=True)
    values = jax.lax.all_gather(g.values, axis_name, tiled=True)
    return RowSparseGrad(rows, values, g.dense_shape)
