"""paddle.audio.features — Spectrogram/Mel/LogMel/MFCC layers.

Reference: python/paddle/audio/features/layers.py.  The STFT lowers to
XLA rfft; the mel projection is one matmul on the MXU.
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import signal as _signal
from ..nn.layer import Layer
from ..framework.tensor import Tensor
from . import functional as AF

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    def __init__(self, n_fft=512, hop_length=None, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        self.n_fft = n_fft
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        self.power = power
        self.center = center
        self.pad_mode = pad_mode
        self.register_buffer(
            "window", AF.get_window(window, self.win_length, dtype=dtype))

    def forward(self, x):
        spec = _signal.stft(x, self.n_fft, hop_length=self.hop_length,
                            win_length=self.win_length, window=self.window,
                            center=self.center, pad_mode=self.pad_mode)
        from ..ops.math import abs as _abs, pow as _pow
        mag = _abs(spec)
        if self.power != 1.0:
            mag = _pow(mag, self.power)
        return mag


class MelSpectrogram(Layer):
    def __init__(self, sr=22050, n_fft=512, hop_length=None,
                 win_length=None, window="hann", power=2.0, center=True,
                 pad_mode="reflect", n_mels=64, f_min=50.0, f_max=None,
                 htk=False, norm="slaney", dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(
            n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            dtype=dtype)
        self.n_mels = n_mels
        self.register_buffer(
            "fbank_matrix",
            AF.compute_fbank_matrix(sr, n_fft, n_mels=n_mels, f_min=f_min,
                                    f_max=f_max, htk=htk, norm=norm,
                                    dtype=dtype))

    def forward(self, x):
        spec = self._spectrogram(x)          # [..., freq, time]
        from ..ops.linalg import matmul
        return matmul(self.fbank_matrix, spec)


class LogMelSpectrogram(Layer):
    def __init__(self, *args, ref_value=1.0, amin=1e-10, top_db=None,
                 **kwargs):
        super().__init__()
        self._melspectrogram = MelSpectrogram(*args, **kwargs)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        mel = self._melspectrogram(x)
        return AF.power_to_db(mel, ref_value=self.ref_value,
                              amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    def __init__(self, sr=22050, n_mfcc=40, n_fft=512, hop_length=None,
                 n_mels=64, f_min=50.0, f_max=None, top_db=None,
                 dtype="float32", **kwargs):
        super().__init__()
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, n_mels=n_mels,
            f_min=f_min, f_max=f_max, top_db=top_db, dtype=dtype, **kwargs)
        self.register_buffer(
            "dct_matrix", AF.create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        logmel = self._log_melspectrogram(x)  # [..., n_mels, time]
        from ..ops.linalg import matmul
        from ..ops.manipulation import transpose
        nd = logmel.ndim
        perm = list(range(nd - 2)) + [nd - 1, nd - 2]
        out = matmul(transpose(logmel, perm), self.dct_matrix)
        return transpose(out, perm)
