"""paddle.audio.functional (reference:
python/paddle/audio/functional/{functional,window}.py)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..ops.registry import op

__all__ = ["hz_to_mel", "mel_to_hz", "mel_frequencies", "fft_frequencies",
           "compute_fbank_matrix", "create_dct", "power_to_db",
           "get_window"]


def hz_to_mel(freq, htk=False):
    scalar = not hasattr(freq, "__len__") and not isinstance(freq, Tensor)
    f = np.asarray(freq.numpy() if isinstance(freq, Tensor) else freq,
                   dtype="float64")
    if htk:
        mel = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mel = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        if mel.ndim:
            log_t = f >= min_log_hz
            mel = np.where(log_t, min_log_mel + np.log(
                np.maximum(f, min_log_hz) / min_log_hz) / logstep, mel)
        elif f >= min_log_hz:
            mel = min_log_mel + math.log(f / min_log_hz) / logstep
    return float(mel) if scalar else mel


def mel_to_hz(mel, htk=False):
    scalar = not hasattr(mel, "__len__") and not isinstance(mel, Tensor)
    m = np.asarray(mel.numpy() if isinstance(mel, Tensor) else mel,
                   dtype="float64")
    if htk:
        hz = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        hz = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = math.log(6.4) / 27.0
        if hz.ndim:
            log_t = m >= min_log_mel
            hz = np.where(log_t, min_log_hz * np.exp(
                logstep * (m - min_log_mel)), hz)
        elif m >= min_log_mel:
            hz = min_log_hz * math.exp(logstep * (m - min_log_mel))
    return float(hz) if scalar else hz


def mel_frequencies(n_mels=64, f_min=0.0, f_max=11025.0, htk=False,
                    dtype="float32"):
    mels = np.linspace(hz_to_mel(f_min, htk), hz_to_mel(f_max, htk),
                       n_mels)
    return Tensor(mel_to_hz(mels, htk).astype(dtype))


def fft_frequencies(sr, n_fft, dtype="float32"):
    return Tensor(np.linspace(0, sr / 2, 1 + n_fft // 2).astype(dtype))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None,
                         htk=False, norm="slaney", dtype="float32"):
    """[n_mels, 1 + n_fft//2] mel filterbank (reference:
    functional.py compute_fbank_matrix)."""
    f_max = f_max or sr / 2.0
    fftfreqs = np.linspace(0, sr / 2, 1 + n_fft // 2)
    mel_f = np.asarray(
        mel_frequencies(n_mels + 2, f_min, f_max, htk).numpy(),
        dtype="float64")
    fdiff = np.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights *= enorm[:, None]
    return Tensor(weights.astype(dtype))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    """[n_mels, n_mfcc] DCT-II basis (reference: functional.py
    create_dct)."""
    n = np.arange(n_mels, dtype="float64")
    k = np.arange(n_mfcc, dtype="float64")[None, :]
    dct = np.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        dct[:, 0] *= 1.0 / math.sqrt(2)
        dct *= math.sqrt(2.0 / n_mels)
    else:
        dct *= 2.0
    return Tensor(dct.astype(dtype))


@op
def power_to_db(x, ref_value=1.0, amin=1e-10, top_db=80.0):
    log_spec = 10.0 * (jnp.log10(jnp.maximum(amin, x))
                       - jnp.log10(jnp.maximum(amin, ref_value)))
    if top_db is not None:
        log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
    return log_spec


_WINDOWS = {}


def _window_fn(name):
    def hann(M, sym):
        return _general_cosine(M, [0.5, 0.5], sym)

    def hamming(M, sym):
        return _general_cosine(M, [0.54, 0.46], sym)

    def blackman(M, sym):
        return _general_cosine(M, [0.42, 0.5, 0.08], sym)

    def bohman(M, sym):
        n = _extend(M, sym)
        fac = np.abs(np.linspace(-1, 1, n))
        w = (1 - fac) * np.cos(np.pi * fac) + 1.0 / np.pi * np.sin(
            np.pi * fac)
        return _trunc(w, M, sym)

    def rect(M, sym):
        return np.ones(M)

    def triang(M, sym):
        n = _extend(M, sym)
        i = np.arange(1, (n + 1) // 2 + 1)
        if n % 2 == 0:
            w = (2 * i - 1.0) / n
            w = np.concatenate([w, w[::-1]])
        else:
            w = 2 * i / (n + 1.0)
            w = np.concatenate([w, w[-2::-1]])
        return _trunc(w, M, sym)

    return {"hann": hann, "hamming": hamming, "blackman": blackman,
            "bohman": bohman, "rect": rect, "boxcar": rect,
            "triang": triang}[name]


def _extend(M, sym):
    return M if sym else M + 1


def _trunc(w, M, sym):
    return w if sym else w[:-1]


def _general_cosine(M, a, sym):
    n = _extend(M, sym)
    fac = np.linspace(-np.pi, np.pi, n)
    w = np.zeros(n)
    for k, coeff in enumerate(a):
        w += coeff * np.cos(k * fac)
    return _trunc(w, M, sym)


def get_window(window, win_length, fftbins=True, dtype="float32"):
    """Reference: window.py get_window."""
    if isinstance(window, tuple):
        name = window[0]
    else:
        name = window
    w = _window_fn(name)(win_length, sym=not fftbins)
    return Tensor(np.asarray(w, dtype=dtype))
