"""paddle.audio.datasets — TESS + ESC50 over a LOCAL pre-extracted
archive dir (reference: python/paddle/audio/datasets/{tess,esc50,
dataset}.py; this is a zero-egress environment, so `data_dir` replaces
the reference's DATA_HOME download — same layout, same folds, same
label lists, same feat_type pipeline)."""
from __future__ import annotations

import os

__all__ = ["TESS", "ESC50", "AudioClassificationDataset"]


class AudioClassificationDataset:
    """Base: (waveform-or-feature, label) records (reference
    audio/datasets/dataset.py AudioClassificationDataset)."""

    _FEATS = ("raw", "spectrogram", "melspectrogram",
              "logmelspectrogram", "mfcc")

    def __init__(self, files, labels, feat_type="raw", sample_rate=None,
                 **feat_config):
        if feat_type not in self._FEATS:
            raise RuntimeError(f"Unknown feat_type: {feat_type}, it must "
                               f"be one in {list(self._FEATS)}")
        self.files = files
        self.labels = labels
        self.feat_type = feat_type
        self.sample_rate = sample_rate
        self.feat_config = feat_config

    def _feat_layer(self, sr):
        # one feature layer per sample rate: the mel filterbank / DCT
        # matrices are sr-dependent and expensive to rebuild per item
        cache = self.__dict__.setdefault("_feat_layers", {})
        layer = cache.get(sr)
        if layer is None:
            from . import features
            cls = {"spectrogram": features.Spectrogram,
                   "melspectrogram": features.MelSpectrogram,
                   "logmelspectrogram": features.LogMelSpectrogram,
                   "mfcc": features.MFCC}[self.feat_type]
            kw = dict(self.feat_config)
            if self.feat_type != "spectrogram":
                kw.setdefault("sr", sr)
            layer = cache[sr] = cls(**kw)
        return layer

    def __getitem__(self, idx):
        from .. import to_tensor
        from . import load

        waveform, sr = load(self.files[idx])
        self.sample_rate = sr
        wav = to_tensor(waveform, dtype="float32")
        if len(wav.shape) == 2:
            wav = wav[0]
        if self.feat_type == "raw":
            return wav, self.labels[idx]
        feat = self._feat_layer(sr)(wav.unsqueeze(0))
        return feat[0], self.labels[idx]

    def __len__(self):
        return len(self.files)


def _wav_walk(root):
    out = []
    for dirpath, _, files in sorted(os.walk(root)):
        for f in sorted(files):
            if f.endswith(".wav"):
                out.append(os.path.join(dirpath, f))
    return out


def _need_dir(data_dir, name, hint):
    if data_dir is None or not os.path.isdir(data_dir):
        raise NotImplementedError(
            f"{name} requires downloading {hint}; there is no network "
            f"egress here — pre-extract the archive and pass "
            f"data_dir=<extracted dir>")
    return data_dir


class TESS(AudioClassificationDataset):
    """Toronto emotional speech set (reference audio/datasets/tess.py):
    2800 wavs named <speaker>_<word>_<emotion>.wav; labels from the
    filename, deterministic interleaved folds."""

    label_list = ["angry", "disgust", "fear", "happy", "neutral",
                  "ps", "sad"]
    audio_path = "TESS_Toronto_emotional_speech_set"

    def __init__(self, mode="train", n_folds=5, split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        assert isinstance(n_folds, int) and n_folds >= 1, n_folds
        assert split in range(1, n_folds + 1), (split, n_folds)
        data_dir = _need_dir(data_dir, "TESS",
                             "the Toronto emotional speech set archive")
        sub = os.path.join(data_dir, self.audio_path)
        wav_files = _wav_walk(sub if os.path.isdir(sub) else data_dir)
        if not wav_files:
            raise RuntimeError(f"no .wav files under {data_dir}")
        files, labels = [], []
        for idx, path in enumerate(wav_files):
            emotion = os.path.basename(path)[:-4].split("_")[-1].lower()
            if emotion not in self.label_list:
                continue
            fold = idx % n_folds + 1
            keep = fold != split if mode == "train" else fold == split
            if keep:
                files.append(path)
                labels.append(self.label_list.index(emotion))
        super().__init__(files, labels, feat_type, **kwargs)


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference audio/datasets/esc50.py):
    meta/esc50.csv assigns each of 2000 wavs a fold and target."""

    meta = os.path.join("meta", "esc50.csv")
    audio_path = "audio"
    prefix = "ESC-50-master"

    def __init__(self, mode="train", split=1, feat_type="raw",
                 data_dir=None, **kwargs):
        assert split in range(1, 6), split
        data_dir = _need_dir(data_dir, "ESC50",
                             "the ESC-50 environmental sound archive")
        base = data_dir
        if os.path.isdir(os.path.join(data_dir, self.prefix)):
            base = os.path.join(data_dir, self.prefix)
        meta_path = os.path.join(base, self.meta)
        if not os.path.isfile(meta_path):
            raise RuntimeError(f"missing {meta_path}")
        files, labels = [], []
        with open(meta_path) as rf:
            for line in rf.readlines()[1:]:
                parts = line.strip().split(",")
                fname, fold, target = parts[0], int(parts[1]), \
                    int(parts[2])
                keep = fold != split if mode == "train" else fold == split
                if keep:
                    files.append(os.path.join(base, self.audio_path,
                                              fname))
                    labels.append(target)
        super().__init__(files, labels, feat_type, **kwargs)
