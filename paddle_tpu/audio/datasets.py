"""paddle.audio.datasets (reference: python/paddle/audio/datasets/{tess,
esc50}.py).  Zero-egress environment: constructors raise with guidance."""
from __future__ import annotations

__all__ = ["TESS", "ESC50"]


def _gated(name, url_hint):
    class _DS:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"{name} requires downloading {url_hint}; there is no "
                "network egress here — pre-extract the archive and wrap it "
                "with paddle.io.Dataset")
    _DS.__name__ = name
    return _DS


TESS = _gated("TESS", "the Toronto emotional speech set archive")
ESC50 = _gated("ESC50", "the ESC-50 environmental sound archive")
