"""paddle.audio — audio feature extraction.

Reference: python/paddle/audio/ (2.5k LoC: features/layers.py
Spectrogram/MelSpectrogram/LogMelSpectrogram/MFCC, functional/window.py
get_window, functional/functional.py hz_to_mel/compute_fbank_matrix/
create_dct).  Built on the framework stft/fft ops, which lower to XLA
FFT on TPU.
"""
from __future__ import annotations

from . import functional  # noqa: F401
from .features import (  # noqa: F401
    Spectrogram, MelSpectrogram, LogMelSpectrogram, MFCC)

__all__ = ["functional", "features", "Spectrogram", "MelSpectrogram",
           "LogMelSpectrogram", "MFCC", "backends"]


class backends:
    """Reference: paddle.audio.backends (soundfile IO). Gated: wave-file
    IO via the stdlib for 16-bit PCM; soundfile is not bundled."""

    @staticmethod
    def load(filepath, frame_offset=0, num_frames=-1, normalize=True):
        import wave

        import numpy as np

        with wave.open(filepath, "rb") as w:
            if w.getsampwidth() != 2:
                raise ValueError(
                    f"only 16-bit PCM wav supported, got "
                    f"{8 * w.getsampwidth()}-bit")
            sr = w.getframerate()
            n = w.getnframes()
            w.setpos(frame_offset)
            count = n - frame_offset if num_frames < 0 else num_frames
            raw = w.readframes(count)
            data = np.frombuffer(raw, dtype="<i2").astype("float32")
            ch = w.getnchannels()
            if ch > 1:
                data = data.reshape(-1, ch).T
            else:
                data = data[None, :]
        if normalize:
            data = data / 32768.0
        from ..framework.tensor import Tensor
        return Tensor(data), sr

    @staticmethod
    def save(filepath, src, sample_rate, channels_first=True,
             bits_per_sample=16):
        import wave

        import numpy as np

        arr = np.asarray(src.numpy() if hasattr(src, "numpy") else src)
        if arr.ndim == 1:
            arr = arr[None, :]
        if not channels_first:
            arr = arr.T
        pcm = np.clip(arr * 32768.0, -32768, 32767).astype("<i2")
        with wave.open(filepath, "wb") as w:
            w.setnchannels(pcm.shape[0])
            w.setsampwidth(2)
            w.setframerate(sample_rate)
            w.writeframes(pcm.T.tobytes())

    @staticmethod
    def info(filepath):
        import wave

        class Info:
            pass

        with wave.open(filepath, "rb") as w:
            i = Info()
            i.sample_rate = w.getframerate()
            i.num_channels = w.getnchannels()
            i.num_frames = w.getnframes()
            i.bits_per_sample = 8 * w.getsampwidth()
        return i


from . import datasets  # noqa: E402,F401

# top-level IO aliases (reference paddle/audio/__init__.py re-exports)
load = backends.load
save = backends.save
info = backends.info

__all__ += ["datasets", "load", "save", "info"]
