"""Runtime lockset sanitizer: instrumented locks + Eraser-style races.

Dynamic counterpart of :mod:`paddle_tpu.analysis.interlock` — the
static pass cannot see races that only manifest through aliasing,
callbacks, or data-dependent control flow, so this module instruments
the real execution:

* :class:`SanitizedLock` / :class:`SanitizedRLock` are drop-in
  ``threading`` lock replacements that maintain a per-thread held-lock
  stack, record the global acquisition-order graph, and report a
  runtime ABBA inversion (lock B taken under A somewhere, A under B
  somewhere else) the moment the second order is observed — no actual
  deadlock required.  They implement the ``_release_save`` /
  ``_acquire_restore`` / ``_is_owned`` protocol, so a plain
  ``threading.Condition(wrapped_lock)`` works unchanged.
* :class:`TrackedField` is an opt-in descriptor implementing the Eraser
  lockset algorithm per (instance, field): Virgin -> Exclusive(first
  thread) -> Shared/Shared-Modified, intersecting the candidate lockset
  with the locks held at every post-first-thread access; a write with
  an empty candidate set is reported once.
* :func:`lock_wait_graph` snapshots who holds / who waits on every live
  sanitized lock (the watchdog embeds it in hang dumps).

Violations become :class:`~paddle_tpu.analysis.core.Finding` records
(rules ``sanitizer-lock-order`` / ``sanitizer-lockset``) attributed to
the acquire/access site, deduplicated by fingerprint, and retrievable
via :func:`findings` — the same schema, reporters, and suppression
vocabulary as the static suite.

Production code never constructs these classes directly: it calls the
:func:`make_lock` / :func:`make_rlock` / :func:`make_condition`
factories, which return plain ``threading`` primitives unless
``FLAGS_sanitizer`` is set — zero overhead when off.
"""
from __future__ import annotations

import os
import sys
import threading

from ..analysis.core import Finding

__all__ = ["RULES", "SanitizedLock", "SanitizedRLock", "TrackedField",
           "enabled", "make_lock", "make_rlock", "make_condition",
           "findings", "clear", "render", "lock_wait_graph"]

RULES = {
    "sanitizer-lock-order": "runtime lock acquisition inverts an "
                            "order observed earlier (ABBA)",
    "sanitizer-lockset": "shared field accessed by multiple threads "
                         "with an empty candidate lockset",
}

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
# frames to skip when attributing a violation to user code: this module
# and threading.py (Condition drives the wrapper through its protocol)
_SKIP_FILES = {os.path.abspath(__file__),
               os.path.abspath(threading.__file__)}

# module-internal mutexes are PLAIN locks — instrumenting the
# instrumentation would recurse
_graph_lock = threading.Lock()
_order: dict[tuple, tuple] = {}       # (outer, inner) -> first site
_order_reported: set = set()          # frozenset({a, b}) pairs
_threads: dict[int, list] = {}        # ident -> that thread's held list
_locks: list = []                     # weakrefs of live sanitized locks

_tls = threading.local()


def _held() -> list:
    h = getattr(_tls, "held", None)
    if h is None:
        h = _tls.held = []
        with _graph_lock:
            _threads[threading.get_ident()] = h
            if len(_threads) > 256:     # prune dead handler threads
                live = {t.ident for t in threading.enumerate()}
                for ident in [i for i in _threads if i not in live]:
                    del _threads[ident]
    return h


def _call_site() -> tuple:
    """(repo-relative path, line) of the nearest frame outside this
    module — the acquire/access site violations are attributed to."""
    f = sys._getframe(1)
    while f is not None and \
            os.path.abspath(f.f_code.co_filename) in _SKIP_FILES:
        f = f.f_back
    if f is None:                       # pragma: no cover - defensive
        return "<unknown>", 0
    path = os.path.abspath(f.f_code.co_filename)
    if path.startswith(_REPO_ROOT + os.sep):
        path = os.path.relpath(path, _REPO_ROOT)
    return path.replace(os.sep, "/"), f.f_lineno


# --------------------------------------------------------------- report
class _Reporter:
    def __init__(self):
        self._lock = threading.Lock()
        self._findings: list[Finding] = []
        self._fps: set = set()

    def report(self, rule, path, line, message, hint=""):
        f = Finding(rule, path, line, message, severity="error",
                    hint=hint)
        with self._lock:
            if f.fingerprint in self._fps:
                return
            self._fps.add(f.fingerprint)
            self._findings.append(f)
        if getattr(_tls, "reporting", False):
            return                      # no recursive flight events
        _tls.reporting = True
        try:        # best-effort breadcrumb in the flight ring
            from .. import observability as _obs
            _obs.flight("sanitizer", rule, path=path, line=line,
                        message=message)
        except Exception:
            pass
        finally:
            _tls.reporting = False

    def findings(self) -> list[Finding]:
        with self._lock:
            return list(self._findings)

    def clear(self):
        with self._lock:
            self._findings.clear()
            self._fps.clear()


_reporter = _Reporter()


def findings() -> list[Finding]:
    """All violations observed so far (deduplicated, stable order)."""
    return _reporter.findings()


def clear():
    """Drop recorded findings and the observed order graph (tests)."""
    _reporter.clear()
    with _graph_lock:
        _order.clear()
        _order_reported.clear()


def render() -> str:
    """Text report through the shared analysis reporters."""
    from ..analysis.reporters import render_text
    return render_text(findings())


# ---------------------------------------------------------------- locks
class SanitizedLock:
    """Instrumented ``threading.Lock`` (reentrant in the subclass).

    The wrapper never recursively acquires ``_inner`` — reentrancy is
    counted here — so ``_inner`` stays a plain Lock even for the RLock
    variant, and ``Condition`` integration releases it exactly once.
    """

    _reentrant = False

    def __init__(self, name: str | None = None):
        self._inner = threading.Lock()
        site = _call_site()
        self.name = name or f"{site[0]}:{site[1]}"
        self._owner: int | None = None
        self._owner_name = ""
        self._count = 0
        self._waiters: dict[int, str] = {}
        with _graph_lock:
            _locks.append(self)
            if len(_locks) > 4096:      # bound unbounded-creation use
                del _locks[:2048]

    # ------------------------------------------------------ acquisition
    def acquire(self, blocking=True, timeout=-1):
        ident = threading.get_ident()
        if self._reentrant and self._owner == ident:
            # tpu-lint: disable=lock-unlocked-write
            self._count += 1        # re-entry: we already own the lock
            return True
        held = _held()
        self._check_order(held)
        me = threading.current_thread().name
        with _graph_lock:
            self._waiters[ident] = me
        try:
            ok = self._inner.acquire(blocking, timeout)
        finally:
            with _graph_lock:
                self._waiters.pop(ident, None)
        if not ok:
            return False
        self._owner = ident
        self._owner_name = me
        self._count = 1
        held.append(self)
        return True

    def release(self):
        ident = threading.get_ident()
        owner = self._owner
        if owner is None:
            raise RuntimeError(f"release of unacquired {self.name}")
        if self._reentrant and owner != ident:
            raise RuntimeError(
                f"release of RLock {self.name} by non-owner thread")
        if self._reentrant and self._count > 1:
            # tpu-lint: disable=lock-unlocked-write
            self._count -= 1        # owner-only path: no race possible
            return
        self._drop()

    def _drop(self):
        # owner bookkeeping precedes the inner release on purpose: the
        # moment _inner is free another thread may acquire and set a
        # new owner, which must not be overwritten afterwards — the
        # inner lock itself orders these writes
        owner = self._owner
        # tpu-lint: disable=lock-unlocked-write
        self._owner = None
        # tpu-lint: disable=lock-unlocked-write
        self._count = 0
        with _graph_lock:           # plain Lock allows cross-thread
            held = _threads.get(owner)  # release: fix the OWNER's stack
        if held is not None:
            for i in range(len(held) - 1, -1, -1):
                if held[i] is self:
                    del held[i]
                    break
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    # ------------------------------------- threading.Condition protocol
    def _is_owned(self):
        return self._owner == threading.get_ident()

    def _release_save(self):
        ident = threading.get_ident()
        if self._owner != ident:
            raise RuntimeError(f"wait on {self.name} by non-owner")
        count = self._count
        self._drop()
        return count

    def _acquire_restore(self, count):
        self.acquire()
        # tpu-lint: disable=lock-unlocked-write
        self._count = count         # we own the lock again right here

    # ------------------------------------------------------ order graph
    def _check_order(self, held):
        if not held:
            return
        site = _call_site()
        to_report = []
        with _graph_lock:
            for outer in held:
                if outer is self or outer.name == self.name:
                    continue            # reentrant / same-site lock
                edge = (outer.name, self.name)
                if edge not in _order:
                    _order[edge] = site
                rev = _order.get((self.name, outer.name))
                if rev is None:
                    continue
                pair = frozenset(edge)
                if pair not in _order_reported:
                    _order_reported.add(pair)
                    to_report.append((outer.name, rev))
        # report OUTSIDE _graph_lock: the flight recorder takes its own
        # (possibly sanitized) locks
        for outer_name, rev in to_report:
            _reporter.report(
                "sanitizer-lock-order", site[0], site[1],
                f"lock {self.name} acquired while holding "
                f"{outer_name}, but the opposite order was observed "
                f"at {rev[0]}:{rev[1]} (runtime ABBA — a deadlock "
                "waiting for the right interleaving)",
                hint="pick one global order for these locks and "
                     "acquire them in that order everywhere")

    def __repr__(self):
        state = f"owner={self._owner_name!r}" if self._owner else "free"
        return f"<{type(self).__name__} {self.name} {state}>"


class SanitizedRLock(SanitizedLock):
    _reentrant = True


# ------------------------------------------------------- Eraser lockset
_VIRGIN, _EXCLUSIVE, _SHARED, _SHARED_MOD = range(4)


class _FieldMonitor:
    """Eraser state machine for one (instance, field)."""

    __slots__ = ("label", "state", "first", "lockset", "reported",
                 "_lock")

    def __init__(self, label):
        self.label = label
        self.state = _VIRGIN
        self.first = None
        self.lockset = None             # frozen candidate set, lazily
        self.reported = False
        self._lock = threading.Lock()   # plain: monitor internals

    def access(self, write: bool):
        ident = threading.get_ident()
        held = frozenset(lk.name for lk in _held())
        fire = None
        with self._lock:
            if self.state == _VIRGIN:
                self.state = _EXCLUSIVE
                self.first = ident
            elif self.state == _EXCLUSIVE and ident == self.first:
                pass                    # still single-threaded
            else:
                self.lockset = held if self.lockset is None \
                    else self.lockset & held
                if write or self.state == _SHARED_MOD:
                    self.state = _SHARED_MOD
                else:
                    self.state = _SHARED
                if self.state == _SHARED_MOD and not self.lockset \
                        and not self.reported:
                    self.reported = True
                    fire = _call_site()
        if fire is not None:
            _reporter.report(
                "sanitizer-lockset", fire[0], fire[1],
                f"field {self.label} is accessed by multiple threads "
                "with an empty candidate lockset (no single lock "
                "protects every access) — Eraser-style data race",
                hint="guard every access with one lock, or document "
                     "the hand-off that makes this safe")


class TrackedField:
    """Opt-in shared-field monitor (fixtures/tests — every access goes
    through a descriptor, so not for hot production paths).

    ``count = TrackedField(0)`` on a class body makes every read/write
    of ``obj.count`` feed the Eraser state machine with the locks the
    accessing thread currently holds (sanitized locks only)."""

    def __init__(self, default=None):
        self.default = default
        self.name = "?"
        self.owner_name = "?"

    def __set_name__(self, owner, name):
        self.name = name
        self.owner_name = owner.__name__

    def _monitor(self, obj) -> _FieldMonitor:
        key = f"_tracked_monitor_{self.name}"
        mon = obj.__dict__.get(key)
        if mon is None:     # setdefault: one monitor even under races
            mon = obj.__dict__.setdefault(
                key, _FieldMonitor(f"{self.owner_name}.{self.name}"))
        return mon

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        self._monitor(obj).access(write=False)
        return obj.__dict__.get(f"_tracked_value_{self.name}",
                                self.default)

    def __set__(self, obj, value):
        self._monitor(obj).access(write=True)
        obj.__dict__[f"_tracked_value_{self.name}"] = value


# ------------------------------------------------------ lock-wait graph
def lock_wait_graph() -> dict:
    """Snapshot of held/waited sanitized locks: per-thread held stacks,
    per-lock owner + waiters, waiter->owner edges, and any wait cycles
    (live deadlocks).  Safe to call from the watchdog while the engine
    is wedged — takes only the sanitizer's internal lock."""
    live = {t.ident: t.name for t in threading.enumerate()}
    with _graph_lock:
        locks_snap = [(lk.name, lk._owner, lk._owner_name,
                       dict(lk._waiters)) for lk in _locks]
        held_snap = {ident: [lk.name for lk in hl]
                     for ident, hl in _threads.items()
                     if ident in live and hl}
    locks_out, edges, waits_on = [], [], {}
    for name, owner, owner_name, waiters in locks_snap:
        if owner is None and not waiters:
            continue                    # idle lock: noise
        locks_out.append({"lock": name, "owner": owner,
                          "owner_name": owner_name or None,
                          "waiters": sorted(waiters.values())})
        for wident, wname in waiters.items():
            if owner is not None:
                edges.append({"waiter": wname, "owner": owner_name,
                              "lock": name})
                waits_on.setdefault(wident, set()).add(owner)
    cycles = _wait_cycles(waits_on, live)
    return {"threads": {live[i]: names for i, names in
                        held_snap.items() if i in live},
            "locks": locks_out, "wait_edges": edges,
            "deadlocks": cycles}


def _wait_cycles(waits_on, live) -> list:
    cycles, seen = [], set()
    for start in waits_on:
        path, node = [], start
        on_path = {}
        while node in waits_on and node not in on_path:
            on_path[node] = len(path)
            path.append(node)
            node = next(iter(waits_on[node]))
        if node in on_path:
            cyc = path[on_path[node]:]
            key = frozenset(cyc)
            if key not in seen:
                seen.add(key)
                cycles.append([live.get(i, str(i)) for i in cyc])
    return cycles


# ------------------------------------------------------------ factories
def enabled() -> bool:
    from ..flags import FLAGS
    return bool(FLAGS.get("FLAGS_sanitizer"))


def make_lock(name: str | None = None):
    """A mutex: plain ``threading.Lock`` normally, instrumented under
    ``FLAGS_sanitizer``.  ``name`` stabilizes the lock's identity in
    reports across instances (default: creation site)."""
    if not enabled():
        return threading.Lock()
    return SanitizedLock(name)


def make_rlock(name: str | None = None):
    if not enabled():
        return threading.RLock()
    return SanitizedRLock(name)


def make_condition(lock=None, name: str | None = None):
    """A ``threading.Condition`` over ``lock`` (or a fresh RLock from
    the factory).  A sanitized lock passed in keeps its wrapper — the
    Condition drives it through the ``_release_save`` protocol, so the
    held-lock stack stays consistent across ``wait()``."""
    if lock is None:
        lock = make_rlock(name)
    return threading.Condition(lock)
