"""paddle_tpu.sanitizer — runtime concurrency sanitizer.

Dynamic counterpart to ``paddle_tpu.analysis``'s lock-discipline
passes: instrumented Lock/RLock/Condition wrappers (lock-order
recording + runtime ABBA detection), Eraser-style per-field candidate
locksets, and a live lock-wait graph for hang dumps.

Production code adopts the ``make_lock``/``make_rlock``/
``make_condition`` factories; with ``FLAGS_sanitizer`` off they return
plain ``threading`` primitives, so the instrumented path costs nothing
unless explicitly enabled (env ``FLAGS_sanitizer=1`` or
``set_flags({"FLAGS_sanitizer": True})``).

Findings use the same schema/fingerprints/reporters as the static
suite and surface in the flight recorder under the "sanitizer" track.
"""
from .lockset import (RULES, SanitizedLock, SanitizedRLock,  # noqa: F401
                      TrackedField, clear, enabled, findings,
                      lock_wait_graph, make_condition, make_lock,
                      make_rlock, render)

__all__ = ["RULES", "SanitizedLock", "SanitizedRLock", "TrackedField",
           "clear", "enabled", "findings", "lock_wait_graph",
           "make_condition", "make_lock", "make_rlock", "render"]
