"""Vision ops (reference: python/paddle/vision/ops.py — yolo/roi/deform ops;
the TPU-relevant subset as pure-jax ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import op

__all__ = ["nms", "box_iou", "roi_align", "DeformConv2D"]


@op(name="box_iou")
def box_iou(boxes1, boxes2):
    """IoU matrix between [N,4] and [M,4] xyxy boxes."""
    a1, a2 = boxes1[:, None, :], boxes2[None, :, :]
    lt = jnp.maximum(a1[..., :2], a2[..., :2])
    rb = jnp.minimum(a1[..., 2:], a2[..., 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area1 = (boxes1[:, 2] - boxes1[:, 0]) * (boxes1[:, 3] - boxes1[:, 1])
    area2 = (boxes2[:, 2] - boxes2[:, 0]) * (boxes2[:, 3] - boxes2[:, 1])
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-9)


@op(name="nms")
def nms(boxes, iou_threshold=0.3, scores=None):
    """Greedy NMS with static shapes (jit-safe): returns keep mask [N].
    The reference returns kept indices (dynamic); under XLA the static
    mask + top-k pattern is idiomatic."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    iou = box_iou.__op_body__(b, b)

    def body(i, keep):
        sup = jnp.logical_and(keep, iou[i] > iou_threshold)
        sup = sup.at[i].set(False)
        return jnp.where(keep[i], jnp.logical_and(keep, ~sup), keep)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    inv = jnp.argsort(order)
    return keep[inv]


@op(name="roi_align")
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign, NCHW input, boxes [K,4] xyxy in input scale; boxes_num
    [N] gives how many of the K boxes belong to each batch image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        # static-shape batch index: box i belongs to the image whose
        # cumulative box count first exceeds i
        ends = jnp.cumsum(jnp.asarray(boxes_num))
        batch_idx = jnp.searchsorted(ends, jnp.arange(k), side="right")

    def one_roi(box, bi):
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = (box * spatial_scale) - off
        rh = jnp.maximum(y2 - y1, 1.0)
        rw = jnp.maximum(x2 - x1, 1.0)
        ys = y1 + (jnp.arange(oh) + 0.5) * rh / oh
        xs = x1 + (jnp.arange(ow) + 0.5) * rw / ow
        y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(ys - y0, 0, 1)[None, :, None]
        wx = jnp.clip(xs - x0, 0, 1)[None, None, :]
        f = x[bi]
        out = (f[:, y0[:, None], x0[None, :]] * (1 - wy) * (1 - wx)
               + f[:, y1i[:, None], x0[None, :]] * wy * (1 - wx)
               + f[:, y0[:, None], x1i[None, :]] * (1 - wy) * wx
               + f[:, y1i[:, None], x1i[None, :]] * wy * wx)
        return out

    return jax.vmap(one_roi)(boxes, batch_idx)


class DeformConv2D:
    def __init__(self, *a, **k):
        raise NotImplementedError(
            "DeformConv2D needs data-dependent gather patterns that map "
            "poorly to TPU; out of scope (reference: vision/ops.py "
            "DeformConv2D)")
