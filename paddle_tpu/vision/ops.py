"""Vision ops (reference: python/paddle/vision/ops.py — yolo/roi/deform ops;
the TPU-relevant subset as pure-jax ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import op

__all__ = ["nms", "nms_mask", "box_iou", "roi_align", "roi_pool", "psroi_pool",
           "box_coder", "prior_box", "yolo_box", "yolo_loss", "matrix_nms",
           "deform_conv2d", "distribute_fpn_proposals", "generate_proposals",
           "read_file", "decode_jpeg", "RoIAlign", "RoIPool", "PSRoIPool",
           "DeformConv2D"]


@op(name="box_iou")
def box_iou(boxes1, boxes2, offset=0.0):
    """IoU matrix between [N,4] and [M,4] xyxy boxes; offset=1 for
    pixel-coordinate (non-normalized) boxes."""
    a1, a2 = boxes1[:, None, :], boxes2[None, :, :]
    lt = jnp.maximum(a1[..., :2], a2[..., :2])
    rb = jnp.minimum(a1[..., 2:], a2[..., 2:])
    wh = jnp.clip(rb - lt + offset, 0)
    inter = wh[..., 0] * wh[..., 1]
    area1 = ((boxes1[:, 2] - boxes1[:, 0] + offset)
             * (boxes1[:, 3] - boxes1[:, 1] + offset))
    area2 = ((boxes2[:, 2] - boxes2[:, 0] + offset)
             * (boxes2[:, 3] - boxes2[:, 1] + offset))
    return inter / (area1[:, None] + area2[None, :] - inter + 1e-9)


def nms_mask(boxes, iou_threshold=0.3, scores=None, category_idxs=None):
    """Greedy NMS as a static-shape keep mask [N] (jit-safe; the XLA
    idiom for in-graph NMS).  With category_idxs, overlaps across
    different categories never suppress (batched/categorical NMS)."""
    n = boxes.shape[0]
    if scores is None:
        order = jnp.arange(n)
    else:
        order = jnp.argsort(-scores)
    b = boxes[order]
    iou = box_iou.__op_body__(b, b)
    if category_idxs is not None:
        cats = jnp.asarray(category_idxs)[order]
        iou = jnp.where(cats[:, None] == cats[None, :], iou, 0.0)

    def body(i, keep):
        sup = jnp.logical_and(keep, iou[i] > iou_threshold)
        sup = sup.at[i].set(False)
        return jnp.where(keep[i], jnp.logical_and(keep, ~sup), keep)

    keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
    inv = jnp.argsort(order)
    return keep[inv]


@op(name="nms")
def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS returning kept box INDICES, score-descending when
    scores are given — the reference contract
    (python/paddle/vision/ops.py:1934 nms), including categorical NMS
    (category_idxs/categories) and top_k.  The result length is
    data-dependent, so this is an eager op; inside jit use `nms_mask`."""
    if categories is not None and category_idxs is None:
        raise ValueError("category_idxs is required when categories is set")
    keep = nms_mask(boxes, iou_threshold, scores, category_idxs)
    idx = jnp.where(keep)[0]
    if scores is not None:
        idx = idx[jnp.argsort(-jnp.asarray(scores)[idx])]
    if top_k is not None:
        idx = idx[:top_k]
    return idx


@op(name="roi_align")
def roi_align(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    """RoIAlign, NCHW input, boxes [K,4] xyxy in input scale; boxes_num
    [N] gives how many of the K boxes belong to each batch image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    n, c, h, w = x.shape
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        # static-shape batch index: box i belongs to the image whose
        # cumulative box count first exceeds i
        ends = jnp.cumsum(jnp.asarray(boxes_num))
        batch_idx = jnp.searchsorted(ends, jnp.arange(k), side="right")

    # grid points per bin (reference roi_align_kernel.cu:113-127 averages
    # a roi_bin_grid of sampling_ratio^2 samples; its adaptive
    # ceil(roi/pooled) rule is data-dependent, which XLA's static shapes
    # can't express — we use 2, the adaptive value for the typical
    # roi ≈ 2x output case)
    g = sampling_ratio if sampling_ratio > 0 else 2

    def one_roi(box, bi):
        off = 0.5 if aligned else 0.0
        x1, y1, x2, y2 = (box * spatial_scale) - off
        rh = y2 - y1
        rw = x2 - x1
        if not aligned:
            # legacy path only: force ROIs to at least one pixel
            rh = jnp.maximum(rh, 1.0)
            rw = jnp.maximum(rw, 1.0)
        # sample positions: bin j, grid point p -> (j + (p+.5)/g) bins in
        frac = (jnp.arange(g) + 0.5) / g
        ys = y1 + (jnp.arange(oh)[:, None] + frac[None, :]).reshape(-1) \
            * (rh / oh)
        xs = x1 + (jnp.arange(ow)[:, None] + frac[None, :]).reshape(-1) \
            * (rw / ow)
        grid_y = jnp.broadcast_to(ys[:, None], (oh * g, ow * g))
        grid_x = jnp.broadcast_to(xs[None, :], (oh * g, ow * g))
        smp = _bilinear_sample(x[bi], grid_y, grid_x)     # [C, oh*g, ow*g]
        return smp.reshape(c, oh, g, ow, g).mean(axis=(2, 4))

    return jax.vmap(one_roi)(boxes, batch_idx)


def _pair2(v):
    return (v, v) if isinstance(v, int) else tuple(v)


def _bilinear_sample(img, ys, xs):
    """Sample img [C,H,W] at float coords ys/xs (same shape S); zeros
    outside.  Returns [C, *S]."""
    c, h, w = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0
    out = 0.0
    for dy, sy in ((0, 1 - wy), (1, wy)):
        for dx, sx in ((0, 1 - wx), (1, wx)):
            yy = y0 + dy
            xx = x0 + dx
            valid = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
            yi = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
            v = img[:, yi, xi] * jnp.where(valid, sy * sx, 0.0)
            out = out + v
    return out


@op(name="deform_conv2d")
def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable convolution v1/v2 (reference vision/ops.py:766; CUDA
    kernel deformable_conv_kernel.cu).  TPU-native: bilinear gather of the
    kh*kw deformed taps (one big take per corner) then an einsum onto the
    MXU — the im2col structure XLA tiles well."""
    sh, sw = _pair2(stride)
    ph, pw = _pair2(padding)
    dh, dw = _pair2(dilation)
    n, cin, h, w = x.shape
    cout, cpg, kh, kw = weight.shape  # cpg = cin/groups
    _, _, oh, ow = offset.shape
    dg = deformable_groups
    k = kh * kw

    # base sampling grid: [k, oh, ow]
    iy = jnp.arange(oh)[:, None] * sh - ph
    ix = jnp.arange(ow)[None, :] * sw - pw
    ky, kx = jnp.meshgrid(jnp.arange(kh), jnp.arange(kw), indexing="ij")
    base_y = iy[None] + (ky.reshape(-1, 1, 1) * dh)
    base_x = ix[None] + (kx.reshape(-1, 1, 1) * dw)

    off = offset.reshape(n, dg, k, 2, oh, ow)
    ys = base_y[None, None] + off[:, :, :, 0]      # [N, dg, k, oh, ow]
    xs = base_x[None, None] + off[:, :, :, 1]
    if mask is not None:
        m = mask.reshape(n, dg, k, oh, ow)
    else:
        m = jnp.ones((n, dg, k, oh, ow), x.dtype)

    xg = x.reshape(n, dg, cin // dg, h, w)

    def per_image(img_g, ys_i, xs_i, m_i):
        # img_g [dg, cin/dg, h, w]; coords [dg, k, oh, ow]
        def per_dg(img, yy, xx, mm):
            patch = _bilinear_sample(img, yy, xx)   # [cin/dg, k, oh, ow]
            return patch * mm[None]
        return jax.vmap(per_dg)(img_g, ys_i, xs_i, m_i)

    patches = jax.vmap(per_image)(xg, ys, xs, m)    # [N,dg,cin/dg,k,oh,ow]
    patches = patches.reshape(n, cin, k, oh, ow)
    wmat = weight.reshape(groups, cout // groups, cpg, k)
    pg = patches.reshape(n, groups, cpg, k, oh, ow)
    out = jnp.einsum("gock,ngckxy->ngoxy", wmat, pg)
    out = out.reshape(n, cout, oh, ow)
    if bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


@op(name="roi_pool")
def roi_pool(x, boxes, boxes_num=None, output_size=7, spatial_scale=1.0,
             name=None):
    """RoIPool: exact integer-bin max pooling (reference vision/ops.py:1572;
    phi/kernels/gpu/roi_pool_kernel.cu).  Bins realized as masked maxima so
    shapes stay static under jit."""
    oh, ow = _pair2(output_size)
    n, c, h, w = x.shape
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        ends = jnp.cumsum(jnp.asarray(boxes_num))
        batch_idx = jnp.searchsorted(ends, jnp.arange(k), side="right")
    ygrid = jnp.arange(h)
    xgrid = jnp.arange(w)

    def one_roi(box, bi):
        x1 = jnp.round(box[0] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(box[1] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(box[2] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(box[3] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        i = jnp.arange(oh)[:, None]
        j = jnp.arange(ow)[None, :]
        hstart = jnp.clip(y1 + (i * rh) // oh, 0, h)
        hend = jnp.clip(y1 + ((i + 1) * rh + oh - 1) // oh, 0, h)
        wstart = jnp.clip(x1 + (j * rw) // ow, 0, w)
        wend = jnp.clip(x1 + ((j + 1) * rw + ow - 1) // ow, 0, w)
        ymask = ((ygrid[None, None, :] >= hstart[..., None])
                 & (ygrid[None, None, :] < hend[..., None]))  # [oh,ow,h]
        xmask = ((xgrid[None, None, :] >= wstart[..., None])
                 & (xgrid[None, None, :] < wend[..., None]))  # [oh,ow,w]
        mask2d = ymask[..., :, None] & xmask[..., None, :]    # [oh,ow,h,w]
        f = x[bi]                                             # [c,h,w]
        neg = jnp.asarray(-jnp.inf, x.dtype)
        vals = jnp.where(mask2d[None], f[:, None, None], neg)
        out = jnp.max(vals, axis=(-2, -1))
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(boxes, batch_idx)


@op(name="psroi_pool")
def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI average pooling (reference vision/ops.py:1441):
    input channels C = out_c*oh*ow; bin (i,j) of output channel c averages
    input channel (c*oh + i)*ow + j inside the bin."""
    oh, ow = _pair2(output_size)
    n, c, h, w = x.shape
    out_c = c // (oh * ow)
    k = boxes.shape[0]
    if boxes_num is None:
        batch_idx = jnp.zeros((k,), jnp.int32)
    else:
        ends = jnp.cumsum(jnp.asarray(boxes_num))
        batch_idx = jnp.searchsorted(ends, jnp.arange(k), side="right")
    ygrid = jnp.arange(h)
    xgrid = jnp.arange(w)

    def one_roi(box, bi):
        x1 = box[0] * spatial_scale
        y1 = box[1] * spatial_scale
        x2 = box[2] * spatial_scale
        y2 = box[3] * spatial_scale
        rh = jnp.maximum(y2 - y1, 0.1)
        rw = jnp.maximum(x2 - x1, 0.1)
        i = jnp.arange(oh)[:, None]
        j = jnp.arange(ow)[None, :]
        hstart = jnp.floor(y1 + i * rh / oh).astype(jnp.int32)
        hend = jnp.ceil(y1 + (i + 1) * rh / oh).astype(jnp.int32)
        wstart = jnp.floor(x1 + j * rw / ow).astype(jnp.int32)
        wend = jnp.ceil(x1 + (j + 1) * rw / ow).astype(jnp.int32)
        hstart = jnp.clip(hstart, 0, h)
        hend = jnp.clip(hend, 0, h)
        wstart = jnp.clip(wstart, 0, w)
        wend = jnp.clip(wend, 0, w)
        ymask = ((ygrid[None, None, :] >= hstart[..., None])
                 & (ygrid[None, None, :] < hend[..., None]))
        xmask = ((xgrid[None, None, :] >= wstart[..., None])
                 & (xgrid[None, None, :] < wend[..., None]))
        mask2d = (ymask[..., :, None] & xmask[..., None, :]).astype(x.dtype)
        f = x[bi].reshape(out_c, oh, ow, h, w)  # channel (c*oh+i)*ow+j
        s = jnp.einsum("cxyhw,xyhw->cxy", f, mask2d)
        cnt = jnp.maximum(jnp.sum(mask2d, axis=(-2, -1)), 1.0)
        return s / cnt

    return jax.vmap(one_roi)(boxes, batch_idx)


@op(name="box_coder")
def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, axis=0,
              name=None):
    """Encode/decode boxes against priors (reference vision/ops.py:584;
    phi/kernels/cpu/box_coder_kernel.cc)."""
    norm = 0.0 if box_normalized else 1.0
    pw = prior_box[:, 2] - prior_box[:, 0] + norm
    ph = prior_box[:, 3] - prior_box[:, 1] + norm
    px = prior_box[:, 0] + pw * 0.5
    py = prior_box[:, 1] + ph * 0.5
    if prior_box_var is None:
        var = jnp.ones((4,))
    elif isinstance(prior_box_var, (list, tuple)):
        var = jnp.asarray(prior_box_var, jnp.float32)
    else:
        var = prior_box_var
    if code_type == "encode_center_size":
        # target [N,4], priors [M,4] -> out [N, M, 4]
        tw = target_box[:, 2] - target_box[:, 0] + norm
        th = target_box[:, 3] - target_box[:, 1] + norm
        tx = target_box[:, 0] + tw * 0.5
        ty = target_box[:, 1] + th * 0.5
        ox = (tx[:, None] - px[None, :]) / pw[None, :]
        oy = (ty[:, None] - py[None, :]) / ph[None, :]
        ow_ = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
        oh_ = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
        out = jnp.stack([ox, oy, ow_, oh_], axis=-1)
        if var.ndim == 2:
            out = out / var[None, :, :]
        else:
            out = out / var.reshape(1, 1, 4)
        return out
    # decode: target [N,M,4]; prior index sits on target dim `axis`
    if axis == 0:
        px_, py_, pw_, ph_ = (a[:, None] for a in (px, py, pw, ph))
        vshape = (-1, 1, 4) if var.ndim == 2 else (1, 1, 4)
    else:
        px_, py_, pw_, ph_ = (a[None, :] for a in (px, py, pw, ph))
        vshape = (1, -1, 4) if var.ndim == 2 else (1, 1, 4)
    v = var.reshape(vshape)
    tx = target_box[..., 0] * v[..., 0]
    ty = target_box[..., 1] * v[..., 1]
    tw = target_box[..., 2] * v[..., 2]
    th = target_box[..., 3] * v[..., 3]
    cx = tx * pw_ + px_
    cy = ty * ph_ + py_
    cw = jnp.exp(tw) * pw_
    ch = jnp.exp(th) * ph_
    return jnp.stack([cx - cw * 0.5, cy - ch * 0.5,
                      cx + cw * 0.5 - norm, cy + ch * 0.5 - norm], axis=-1)


@op(name="prior_box")
def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior (anchor) box generation (reference vision/ops.py:438;
    phi/kernels/cpu/prior_box_kernel.cc)."""
    _, _, fh, fw = input.shape
    _, _, ih, iw = image.shape
    step_h = steps[1] if steps[1] > 0 else ih / fh
    step_w = steps[0] if steps[0] > 0 else iw / fw
    ars = [1.0]
    for ar in aspect_ratios:
        if not any(abs(ar - e) < 1e-6 for e in ars):
            ars.append(float(ar))
            if flip:
                ars.append(1.0 / float(ar))
    import math as _m
    boxes = []
    for mi, ms in enumerate(min_sizes):
        ms = float(ms)
        if min_max_aspect_ratios_order:
            # reference order: min box, max box, then the other ratios
            boxes.append((ms, ms))
            if max_sizes:
                mx = float(max_sizes[mi])
                boxes.append((_m.sqrt(ms * mx), _m.sqrt(ms * mx)))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                boxes.append((ms * _m.sqrt(ar), ms / _m.sqrt(ar)))
        else:
            for ar in ars:
                boxes.append((ms * _m.sqrt(ar), ms / _m.sqrt(ar)))
            if max_sizes:
                mx = float(max_sizes[mi])
                boxes.append((_m.sqrt(ms * mx), _m.sqrt(ms * mx)))
    num_priors = len(boxes)
    bw = jnp.asarray([b[0] for b in boxes]) * 0.5
    bh = jnp.asarray([b[1] for b in boxes]) * 0.5
    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    cxg, cyg = jnp.meshgrid(cx, cy, indexing="xy")
    out = jnp.stack([
        (cxg[..., None] - bw) / iw, (cyg[..., None] - bh) / ih,
        (cxg[..., None] + bw) / iw, (cyg[..., None] + bh) / ih], axis=-1)
    out = out.reshape(fh, fw, num_priors, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    var = jnp.broadcast_to(jnp.asarray(variance), (fh, fw, num_priors, 4))
    return out, var


@op(name="yolo_box")
def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5, name=None):
    """Decode a YOLOv3 head into boxes + scores (reference
    vision/ops.py:277; phi/kernels/gpu/yolo_box_kernel.cu)."""
    n, c, hh, ww = x.shape
    na = len(anchors) // 2
    anchors_ = jnp.asarray(anchors, jnp.float32).reshape(na, 2)
    if iou_aware:
        ious = jax.nn.sigmoid(x[:, :na].reshape(n, na, hh, ww))
        feats = x[:, na:].reshape(n, na, 5 + class_num, hh, ww)
    else:
        feats = x.reshape(n, na, 5 + class_num, hh, ww)
    gx = jnp.arange(ww, dtype=jnp.float32)
    gy = jnp.arange(hh, dtype=jnp.float32)
    bx = ((jax.nn.sigmoid(feats[:, :, 0]) - 0.5) * scale_x_y + 0.5
          + gx[None, None, None, :]) / ww
    by = ((jax.nn.sigmoid(feats[:, :, 1]) - 0.5) * scale_x_y + 0.5
          + gy[None, None, :, None]) / hh
    input_h = downsample_ratio * hh
    input_w = downsample_ratio * ww
    bw = jnp.exp(feats[:, :, 2]) * anchors_[None, :, 0, None, None] / input_w
    bh = jnp.exp(feats[:, :, 3]) * anchors_[None, :, 1, None, None] / input_h
    conf = jax.nn.sigmoid(feats[:, :, 4])
    if iou_aware:
        conf = conf ** (1 - iou_aware_factor) * ious ** iou_aware_factor
    probs = jax.nn.sigmoid(feats[:, :, 5:]) * conf[:, :, None]
    keep = conf >= conf_thresh
    imh = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    imw = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    x1 = (bx - bw / 2) * imw
    y1 = (by - bh / 2) * imh
    x2 = (bx + bw / 2) * imw
    y2 = (by + bh / 2) * imh
    if clip_bbox:
        x1 = jnp.clip(x1, 0, imw - 1)
        y1 = jnp.clip(y1, 0, imh - 1)
        x2 = jnp.clip(x2, 0, imw - 1)
        y2 = jnp.clip(y2, 0, imh - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1)
    boxes = boxes * keep[..., None]
    boxes = boxes.reshape(n, na * hh * ww, 4)
    scores = (probs * keep[:, :, None]).transpose(0, 1, 3, 4, 2)
    scores = scores.reshape(n, na * hh * ww, class_num)
    return boxes, scores


@op(name="yolo_loss")
def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, scale_x_y=1.0, name=None):
    """YOLOv3 loss (reference vision/ops.py:69; phi/kernels/cpu/
    yolo_loss_kernel.cc): coordinate BCE/L1 + objectness BCE with
    ignore-region, + class BCE.  gt_box is [N,B,4] (cx,cy,w,h) normalized
    to the input image."""
    n, c, hh, ww = x.shape
    na = len(anchor_mask)
    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    mask_anchors = all_anchors[jnp.asarray(anchor_mask)]
    input_size = downsample_ratio * hh
    feats = x.reshape(n, na, 5 + class_num, hh, ww)
    px = jax.nn.sigmoid(feats[:, :, 0])
    py = jax.nn.sigmoid(feats[:, :, 1])
    pw = feats[:, :, 2]
    ph = feats[:, :, 3]
    pobj = feats[:, :, 4]
    pcls = feats[:, :, 5:]

    b = gt_box.shape[1]
    if gt_score is None:
        gt_score = jnp.ones((n, b), x.dtype)

    # best anchor (over ALL anchors) for each gt by wh IoU
    gw = gt_box[..., 2] * input_size
    gh = gt_box[..., 3] * input_size
    inter = (jnp.minimum(gw[..., None], all_anchors[:, 0])
             * jnp.minimum(gh[..., None], all_anchors[:, 1]))
    union = gw[..., None] * gh[..., None] \
        + all_anchors[:, 0] * all_anchors[:, 1] - inter
    best = jnp.argmax(inter / (union + 1e-9), axis=-1)       # [N,B]

    gi = jnp.clip((gt_box[..., 0] * ww).astype(jnp.int32), 0, ww - 1)
    gj = jnp.clip((gt_box[..., 1] * hh).astype(jnp.int32), 0, hh - 1)
    valid = (gt_box[..., 2] > 0) & (gt_box[..., 3] > 0)

    def bce(logit, label):
        return jnp.maximum(logit, 0) - logit * label \
            + jnp.log1p(jnp.exp(-jnp.abs(logit)))

    smooth = 1.0 / class_num if use_label_smooth and class_num > 1 else 0.0

    total = jnp.zeros((n,), x.dtype)
    obj_target = jnp.zeros((n, na, hh, ww), x.dtype)
    obj_weight = jnp.zeros((n, na, hh, ww), x.dtype)
    for local_a, global_a in enumerate(anchor_mask):
        sel = valid & (best == global_a)                      # [N,B]
        wgt = sel.astype(x.dtype) * gt_score
        tx = gt_box[..., 0] * ww - gi
        ty = gt_box[..., 1] * hh - gj
        tw = jnp.log(jnp.clip(gw / all_anchors[global_a, 0], 1e-9))
        th = jnp.log(jnp.clip(gh / all_anchors[global_a, 1], 1e-9))
        scale = 2.0 - gt_box[..., 2] * gt_box[..., 3]
        # raw logits for x/y (sigmoid cross-entropy, like the reference
        # kernel); raw values for w/h (L1)
        lxa = feats[:, local_a, 0]
        lya = feats[:, local_a, 1]
        pwa = pw[:, local_a]
        pha = ph[:, local_a]

        def gather_pred(p):
            return jax.vmap(lambda pm, jj, ii: pm[jj, ii])(p, gj, gi)

        lx = bce(gather_pred(lxa), tx) * scale
        ly = bce(gather_pred(lya), ty) * scale
        lw = jnp.abs(gather_pred(pwa) - tw) * scale
        lh = jnp.abs(gather_pred(pha) - th) * scale
        total = total + jnp.sum((lx + ly + lw + lh) * wgt, axis=1)
        # class loss at positive cells
        cls_at = jax.vmap(lambda pm, jj, ii: pm[:, jj, ii].T)(
            pcls[:, local_a], gj, gi)                        # [N,B,class]
        onehot = jax.nn.one_hot(gt_label, class_num, dtype=x.dtype)
        onehot = onehot * (1 - smooth) + smooth / 2
        lcls = jnp.sum(bce(cls_at, onehot), axis=-1)
        total = total + jnp.sum(lcls * wgt, axis=1)
        # objectness targets
        tgt = jnp.zeros((n, hh, ww), x.dtype)
        tgt = jax.vmap(lambda t_, jj, ii, ww_: t_.at[jj, ii].max(ww_))(
            tgt, gj, gi, wgt)
        obj_target = obj_target.at[:, local_a].set(tgt)
        obj_weight = obj_weight.at[:, local_a].set(
            jnp.ones((n, hh, ww), x.dtype))

    # ignore region: predicted boxes with IoU > thresh vs any gt
    gx_ = jnp.arange(ww, dtype=jnp.float32)
    gy_ = jnp.arange(hh, dtype=jnp.float32)
    bx = (px + gx_[None, None, None, :]) / ww
    by = (py + gy_[None, None, :, None]) / hh
    bw_ = jnp.exp(pw) * mask_anchors[None, :, 0, None, None] / input_size
    bh_ = jnp.exp(ph) * mask_anchors[None, :, 1, None, None] / input_size
    pb = jnp.stack([bx - bw_ / 2, by - bh_ / 2, bx + bw_ / 2, by + bh_ / 2],
                   axis=-1).reshape(n, -1, 4)
    gb = jnp.stack([gt_box[..., 0] - gt_box[..., 2] / 2,
                    gt_box[..., 1] - gt_box[..., 3] / 2,
                    gt_box[..., 0] + gt_box[..., 2] / 2,
                    gt_box[..., 1] + gt_box[..., 3] / 2], axis=-1)

    def iou_many(pb_i, gb_i, valid_i):
        lt = jnp.maximum(pb_i[:, None, :2], gb_i[None, :, :2])
        rb = jnp.minimum(pb_i[:, None, 2:], gb_i[None, :, 2:])
        whi = jnp.clip(rb - lt, 0)
        inter_ = whi[..., 0] * whi[..., 1]
        a1 = ((pb_i[:, 2] - pb_i[:, 0]) * (pb_i[:, 3] - pb_i[:, 1]))[:, None]
        a2 = ((gb_i[:, 2] - gb_i[:, 0]) * (gb_i[:, 3] - gb_i[:, 1]))[None, :]
        iou = inter_ / (a1 + a2 - inter_ + 1e-9)
        return jnp.max(jnp.where(valid_i[None, :], iou, 0.0), axis=1)

    best_iou = jax.vmap(iou_many)(pb, gb, valid)
    ignore = (best_iou > ignore_thresh).reshape(n, na, hh, ww)
    noobj_w = jnp.where((obj_target == 0) & ignore, 0.0, 1.0)
    lobj = bce(pobj, obj_target) * noobj_w * obj_weight
    total = total + jnp.sum(lobj, axis=(1, 2, 3))
    return total


@op(name="matrix_nms")
def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference vision/ops.py:2358; SOLOv2 parallel decay).
    bboxes [N, M, 4], scores [N, C, M]; returns [N, keep_top_k, 6] padded
    (label, decayed_score, x1, y1, x2, y2) plus rois_num (and index)."""
    n, c, m = scores.shape

    def per_image(box, sc):
        # flatten classes (skip background)
        cls_ids = jnp.arange(c)
        keep_cls = cls_ids != background_label
        s = jnp.where(keep_cls[:, None], sc, 0.0)
        s = jnp.where(s > score_threshold, s, 0.0)          # [C, M]
        flat = s.reshape(-1)
        topk = min(nms_top_k, flat.shape[0])
        vals, idx = jax.lax.top_k(flat, topk)
        cls_of = idx // m
        box_of = idx % m
        bsel = box[box_of]                                   # [topk, 4]
        # pixel-coordinate boxes (normalized=False) span an extra +1
        iou = box_iou.__op_body__(bsel, bsel,
                                  offset=0.0 if normalized else 1.0)
        same_cls = cls_of[:, None] == cls_of[None, :]
        upper = jnp.triu(jnp.ones((topk, topk), bool), 1)
        # pair[i, j] = iou(suppressor i, victim j) for i < j (score-sorted)
        pair = jnp.where(same_cls & upper, iou, 0.0)
        # compensation: each suppressor's own max overlap with its betters
        comp = jnp.max(pair, axis=0)
        if use_gaussian:
            d = jnp.exp(-(jnp.square(pair) - jnp.square(comp)[:, None])
                        / gaussian_sigma)
        else:
            d = (1 - pair) / jnp.clip(1 - comp[:, None], 1e-9)
        d = jnp.where(same_cls & upper, d, 1.0)
        decay = jnp.min(d, axis=0)
        new_scores = vals * decay
        new_scores = jnp.where(new_scores >= post_threshold, new_scores, 0.0)
        kk = topk if keep_top_k < 0 else min(keep_top_k, topk)
        fvals, fidx = jax.lax.top_k(new_scores, kk)
        out = jnp.concatenate([
            cls_of[fidx][:, None].astype(box.dtype),
            fvals[:, None], bsel[fidx]], axis=1)
        num = jnp.sum(fvals > 0).astype(jnp.int32)
        return out, num, box_of[fidx]

    outs, nums, idxs = jax.vmap(per_image)(bboxes, scores)
    if return_index:
        return outs, nums, idxs
    if return_rois_num:
        return outs, nums
    return outs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=False, rois_num=None,
                             name=None):
    """Assign RoIs to FPN levels (reference vision/ops.py:1175; FPN paper
    eq.1).  Host-side post-processing — eager only."""
    import numpy as _np
    rois = _np.asarray(fpn_rois.numpy() if hasattr(fpn_rois, "numpy")
                       else fpn_rois)
    off = 1.0 if pixel_offset else 0.0
    ws = rois[:, 2] - rois[:, 0] + off
    hs = rois[:, 3] - rois[:, 1] + off
    scale = _np.sqrt(_np.clip(ws * hs, 0, None))
    lvl = _np.floor(_np.log2(scale / refer_scale + 1e-8)) + refer_level
    lvl = _np.clip(lvl, min_level, max_level).astype(_np.int64)
    from ..framework.tensor import Tensor
    # image id per roi, so per-level counts stay per-image (usable as
    # boxes_num for downstream roi_align)
    if rois_num is not None:
        rn = _np.asarray(rois_num.numpy() if hasattr(rois_num, "numpy")
                         else rois_num).astype(_np.int64)
        img_of = _np.repeat(_np.arange(len(rn)), rn)
        n_img = len(rn)
    else:
        img_of = _np.zeros(len(rois), _np.int64)
        n_img = 1
    multi_rois = []
    rois_num_per_level = []
    order = []
    for L in range(min_level, max_level + 1):
        idx = _np.where(lvl == L)[0]
        multi_rois.append(Tensor(jnp.asarray(rois[idx])))
        per_img = _np.bincount(img_of[idx], minlength=n_img).astype(_np.int32)
        rois_num_per_level.append(Tensor(jnp.asarray(per_img)))
        order.append(idx)
    order = _np.concatenate(order) if order else _np.zeros((0,), _np.int64)
    restore = _np.argsort(order).astype(_np.int32)
    return multi_rois, Tensor(jnp.asarray(restore)), rois_num_per_level


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       pixel_offset=False, return_rois_num=False, name=None):
    """RPN proposal generation (reference vision/ops.py:2106) — decode
    deltas on anchors, clip, filter small, NMS.  Host-side (eager only)."""
    import numpy as _np
    from ..framework.tensor import Tensor
    sc = _np.asarray(scores.numpy() if hasattr(scores, "numpy") else scores)
    bd = _np.asarray(bbox_deltas.numpy() if hasattr(bbox_deltas, "numpy")
                     else bbox_deltas)
    an = _np.asarray(anchors.numpy() if hasattr(anchors, "numpy")
                     else anchors).reshape(-1, 4)
    va = _np.asarray(variances.numpy() if hasattr(variances, "numpy")
                     else variances).reshape(-1, 4)
    imgs = _np.asarray(img_size.numpy() if hasattr(img_size, "numpy")
                       else img_size)
    n = sc.shape[0]
    all_rois, all_probs, nums = [], [], []
    off = 1.0 if pixel_offset else 0.0
    for i in range(n):
        s = sc[i].transpose(1, 2, 0).reshape(-1)
        d = bd[i].transpose(1, 2, 0).reshape(-1, 4)
        order = _np.argsort(-s)[:pre_nms_top_n]
        s = s[order]
        d = d[order]
        a = an[order]
        v = va[order]
        aw = a[:, 2] - a[:, 0] + off
        ah = a[:, 3] - a[:, 1] + off
        ax = a[:, 0] + aw * 0.5
        ay = a[:, 1] + ah * 0.5
        cx = v[:, 0] * d[:, 0] * aw + ax
        cy = v[:, 1] * d[:, 1] * ah + ay
        cw = _np.exp(_np.clip(v[:, 2] * d[:, 2], None, 10)) * aw
        ch = _np.exp(_np.clip(v[:, 3] * d[:, 3], None, 10)) * ah
        boxes = _np.stack([cx - cw / 2, cy - ch / 2,
                           cx + cw / 2 - off, cy + ch / 2 - off], axis=1)
        hh, ww_ = imgs[i][0], imgs[i][1]
        boxes[:, 0] = _np.clip(boxes[:, 0], 0, ww_ - off)
        boxes[:, 1] = _np.clip(boxes[:, 1], 0, hh - off)
        boxes[:, 2] = _np.clip(boxes[:, 2], 0, ww_ - off)
        boxes[:, 3] = _np.clip(boxes[:, 3], 0, hh - off)
        keep = ((boxes[:, 2] - boxes[:, 0] + off >= min_size)
                & (boxes[:, 3] - boxes[:, 1] + off >= min_size))
        boxes, s = boxes[keep], s[keep]
        keep_mask = nms_mask(jnp.asarray(boxes), nms_thresh,
                             jnp.asarray(s))
        km = _np.asarray(keep_mask._data if hasattr(keep_mask, "_data")
                         else keep_mask)
        idx = _np.where(km)[0]
        idx = idx[_np.argsort(-s[idx])][:post_nms_top_n]
        all_rois.append(boxes[idx])
        all_probs.append(s[idx])
        nums.append(len(idx))
    rois = Tensor(jnp.asarray(_np.concatenate(all_rois, 0)
                              if all_rois else _np.zeros((0, 4))))
    probs = Tensor(jnp.asarray(_np.concatenate(all_probs, 0)
                               if all_probs else _np.zeros((0,))))
    nums_t = Tensor(jnp.asarray(_np.asarray(nums, _np.int32)))
    if return_rois_num:
        return rois, probs, nums_t
    return rois, probs


def read_file(filename, name=None):
    """Read raw bytes into a uint8 tensor (reference vision/ops.py
    read_file)."""
    import numpy as _np
    from ..framework.tensor import Tensor
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(_np.frombuffer(data, _np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference vision/ops.py
    decode_jpeg binds nvjpeg; here PIL on host)."""
    import io as _io
    import numpy as _np
    from ..framework.tensor import Tensor
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError(
            "decode_jpeg needs Pillow on the host (nvjpeg has no TPU "
            "analog)") from e
    raw = bytes(_np.asarray(x.numpy() if hasattr(x, "numpy") else x,
                            _np.uint8))
    img = Image.open(_io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = _np.asarray(img)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


class RoIAlign:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self.output_size,
                         self.spatial_scale)


class RoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self.output_size,
                        self.spatial_scale)


class PSRoIPool:
    def __init__(self, output_size, spatial_scale=1.0):
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def __call__(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def _deform_conv_layer():
    """Build the DeformConv2D Layer class lazily so vision.ops has no
    import-time dependency on nn (package init imports nn first)."""
    import math as _m
    from ..nn.layer import Layer
    from ..nn.initializer import Uniform

    class DeformConv2D(Layer):
        """Deformable conv layer (reference vision/ops.py DeformConv2D)."""

        def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                     padding=0, dilation=1, deformable_groups=1, groups=1,
                     weight_attr=None, bias_attr=None):
            super().__init__()
            kh, kw = _pair2(kernel_size)
            fan_in = in_channels * kh * kw
            bound = 1.0 / _m.sqrt(fan_in)
            self.weight = self.create_parameter(
                (out_channels, in_channels // groups, kh, kw),
                attr=weight_attr,
                default_initializer=Uniform(-bound, bound))
            self.bias = None if bias_attr is False else \
                self.create_parameter(
                    (out_channels,), attr=bias_attr, is_bias=True,
                    default_initializer=Uniform(-bound, bound))
            self.args = (stride, padding, dilation, deformable_groups,
                         groups)

        def forward(self, x, offset, mask=None):
            s, p, d, dg, g = self.args
            return deform_conv2d(x, offset, self.weight, self.bias,
                                 stride=s, padding=p, dilation=d,
                                 deformable_groups=dg, groups=g, mask=mask)

    return DeformConv2D


class _LazyDeformConv2D:
    _cls = None

    def __new__(cls, *args, **kwargs):
        if cls._cls is None:
            cls._cls = _deform_conv_layer()
        return cls._cls(*args, **kwargs)


DeformConv2D = _LazyDeformConv2D
