"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: the reference downloads MNIST/Cifar from servers;
here datasets load from a local path when given one and otherwise generate a
deterministic synthetic split with the same shapes/label space, so the
training ladder (BASELINE.md #1/#2) runs hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageNet",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class MNIST(Dataset):
    """28x28 grayscale digits. mode: 'train' | 'test'."""

    _N = {"train": 60000, "test": 10000}

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path and not os.path.exists(image_path):
            raise FileNotFoundError(f"MNIST image_path {image_path!r} does "
                                    "not exist (no download in this env)")
        if image_path and not label_path:
            raise ValueError("label_path is required with image_path")
        if image_path:
            with gzip.open(image_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = synthetic_size or 512
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so a model can actually fit the data
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, y in enumerate(self.labels):
                img = rng.rand(28, 28) * 64
                r, c = divmod(int(y), 4)
                img[r * 7:(r + 1) * 7 + 7, c * 7:c * 7 + 7] += 160
                self.images[i] = np.clip(img, 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.images[idx])
        else:
            img = self.images[idx].astype(np.float32)[None] / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        n = synthetic_size or 256
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        self.labels = rng.randint(0, self.n_classes, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 32, 32, 3)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100


class FakeImageNet(Dataset):
    """Synthetic 224x224 ImageNet-shaped stream for the ResNet50 bench."""

    def __init__(self, size=1024, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self.labels = self._rng.randint(0, num_classes, size).astype(np.int64)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return self.size


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                   ".tiff", ".webp")


class DatasetFolder(Dataset):
    """Generic folder-per-class dataset (reference
    vision/datasets/folder.py DatasetFolder):
    root/class_x/xxx.ext -> (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(tuple(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    @staticmethod
    def _default_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image-folder dataset, no labels (reference
    vision/datasets/folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


def _need_local(path, name, what):
    if path is None or not os.path.exists(path):
        raise NotImplementedError(
            f"{name} requires downloading {what}; there is no network "
            f"egress here — pre-download it and pass the local path")
    return path


class Flowers(Dataset):
    """Flowers-102 over the three LOCAL archive files (reference
    vision/datasets/flowers.py — same tar/mat layout, same
    MODE_FLAG_MAP split semantics; `download` is accepted for API
    parity but files must already exist)."""

    MODE_FLAG_MAP = {"train": "tstid", "test": "trnid", "valid": "valid"}

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        import tarfile

        import scipy.io as scio

        assert mode.lower() in self.MODE_FLAG_MAP, mode
        flag = self.MODE_FLAG_MAP[mode.lower()]
        data_file = _need_local(data_file, "Flowers",
                                "the 102flowers.tgz image archive")
        label_file = _need_local(label_file, "Flowers",
                                 "imagelabels.mat")
        setid_file = _need_local(setid_file, "Flowers", "setid.mat")
        self.transform = transform
        self._tar = tarfile.open(data_file)
        self._names = {os.path.basename(m.name): m
                       for m in self._tar.getmembers()
                       if m.name.endswith(".jpg")}
        self.labels = scio.loadmat(label_file)["labels"][0]
        self.indexes = scio.loadmat(setid_file)[flag][0]

    def __getitem__(self, idx):
        from PIL import Image

        index = int(self.indexes[idx])
        label = int(self.labels[index - 1])
        member = self._names[f"image_{index:05d}.jpg"]
        img = Image.open(self._tar.extractfile(member)).convert("RGB")
        img = np.asarray(img)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.asarray([label])

    def __len__(self):
        return len(self.indexes)


class VOC2012(Dataset):
    """VOC2012 segmentation over a LOCAL archive (reference
    vision/datasets/voc2012.py: members from the tar; mode selects
    ImageSets/Segmentation/{train,val,trainval}.txt)."""

    MODE_FLAG_MAP = {"train": "trainval", "test": "train", "valid": "val"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        import tarfile

        assert mode.lower() in self.MODE_FLAG_MAP, mode
        flag = self.MODE_FLAG_MAP[mode.lower()]
        data_file = _need_local(data_file, "VOC2012",
                                "the VOCtrainval archive")
        self.transform = transform
        self._tar = tarfile.open(data_file)
        members = {m.name: m for m in self._tar.getmembers()}
        list_member = next(
            m for n, m in members.items()
            if n.endswith(f"ImageSets/Segmentation/{flag}.txt"))
        base = list_member.name.rsplit("ImageSets/", 1)[0]
        names = self._tar.extractfile(list_member).read().decode() \
            .split()
        self._pairs = [
            (members[f"{base}JPEGImages/{n}.jpg"],
             members[f"{base}SegmentationClass/{n}.png"])
            for n in names]

    def __getitem__(self, idx):
        from PIL import Image

        im, lm = self._pairs[idx]
        img = np.asarray(Image.open(self._tar.extractfile(im))
                         .convert("RGB"))
        label = np.asarray(Image.open(self._tar.extractfile(lm)))
        if self.transform is not None:
            img = self.transform(img)
        return img, label

    def __len__(self):
        return len(self._pairs)
