"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: the reference downloads MNIST/Cifar from servers;
here datasets load from a local path when given one and otherwise generate a
deterministic synthetic split with the same shapes/label space, so the
training ladder (BASELINE.md #1/#2) runs hermetically.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io.dataset import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeImageNet",
           "DatasetFolder", "ImageFolder", "Flowers", "VOC2012"]


class MNIST(Dataset):
    """28x28 grayscale digits. mode: 'train' | 'test'."""

    _N = {"train": 60000, "test": 10000}

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None,
                 synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        if image_path and not os.path.exists(image_path):
            raise FileNotFoundError(f"MNIST image_path {image_path!r} does "
                                    "not exist (no download in this env)")
        if image_path and not label_path:
            raise ValueError("label_path is required with image_path")
        if image_path:
            with gzip.open(image_path, "rb") as f:
                _, n, rows, cols = struct.unpack(">IIII", f.read(16))
                self.images = np.frombuffer(f.read(), np.uint8).reshape(
                    n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                f.read(8)
                self.labels = np.frombuffer(f.read(), np.uint8)
        else:
            n = synthetic_size or 512
            rng = np.random.RandomState(0 if self.mode == "train" else 1)
            self.labels = rng.randint(0, 10, n).astype(np.int64)
            # class-dependent blobs so a model can actually fit the data
            self.images = np.zeros((n, 28, 28), np.uint8)
            for i, y in enumerate(self.labels):
                img = rng.rand(28, 28) * 64
                r, c = divmod(int(y), 4)
                img[r * 7:(r + 1) * 7 + 7, c * 7:c * 7 + 7] += 160
                self.images[i] = np.clip(img, 0, 255).astype(np.uint8)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.images[idx])
        else:
            img = self.images[idx].astype(np.float32)[None] / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class _CifarBase(Dataset):
    n_classes = 10

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None, synthetic_size=None):
        self.mode = mode.lower()
        self.transform = transform
        n = synthetic_size or 256
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        self.labels = rng.randint(0, self.n_classes, n).astype(np.int64)
        self.images = rng.randint(0, 255, (n, 32, 32, 3)).astype(np.uint8)

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, int(self.labels[idx])

    def __len__(self):
        return len(self.images)


class Cifar10(_CifarBase):
    n_classes = 10


class Cifar100(_CifarBase):
    n_classes = 100


class FakeImageNet(Dataset):
    """Synthetic 224x224 ImageNet-shaped stream for the ResNet50 bench."""

    def __init__(self, size=1024, image_shape=(3, 224, 224), num_classes=1000,
                 transform=None, seed=0):
        self.size = size
        self.image_shape = image_shape
        self.num_classes = num_classes
        self.transform = transform
        self._rng = np.random.RandomState(seed)
        self.labels = self._rng.randint(0, num_classes, size).astype(np.int64)

    def __getitem__(self, idx):
        rng = np.random.RandomState(idx)
        img = rng.rand(*self.image_shape).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, int(self.labels[idx])

    def __len__(self):
        return self.size


_IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                   ".tiff", ".webp")


class DatasetFolder(Dataset):
    """Generic folder-per-class dataset (reference
    vision/datasets/folder.py DatasetFolder):
    root/class_x/xxx.ext -> (sample, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or self._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        if not classes:
            raise RuntimeError(f"no class folders found in {root}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = is_valid_file(path) if is_valid_file else \
                        fname.lower().endswith(tuple(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    @staticmethod
    def _default_loader(path):
        from PIL import Image
        with open(path, "rb") as f:
            return Image.open(f).convert("RGB")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat image-folder dataset, no labels (reference
    vision/datasets/folder.py ImageFolder)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        import os
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        extensions = extensions or _IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = is_valid_file(path) if is_valid_file else \
                    fname.lower().endswith(tuple(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid files found under {root}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Flowers-102 (reference vision/datasets/flowers.py).  Zero-egress:
    requires pre-downloaded files."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        raise NotImplementedError(
            "Flowers needs its three archive files; there is no download "
            "in this environment — place them locally and load with "
            "DatasetFolder, or use FakeImageNet for synthetic data")


class VOC2012(Dataset):
    """VOC2012 segmentation (reference vision/datasets/voc2012.py).
    Zero-egress: requires a pre-downloaded archive."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        raise NotImplementedError(
            "VOC2012 needs its archive; there is no download in this "
            "environment — extract it and load with DatasetFolder")
