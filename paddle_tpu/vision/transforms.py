"""Image transforms (reference: python/paddle/vision/transforms/transforms.py
+ functional.py).  Operate on numpy HWC uint8/float arrays (or CHW tensors
for Normalize with data_format='CHW'), composable before DataLoader batching.
"""
from __future__ import annotations

import numbers
import random

import numpy as np

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Transpose", "BrightnessTransform", "Pad", "RandomResizedCrop",
           "to_tensor", "normalize", "resize", "hflip", "vflip",
           "center_crop", "crop"]


# ------------------------------------------------------------- functional
def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, numbers.Number):
        if h <= w:
            oh, ow = int(size), int(size * w / h)
        else:
            oh, ow = int(size * h / w), int(size)
    else:
        oh, ow = size
    ys = np.linspace(0, h - 1, oh)
    xs = np.linspace(0, w - 1, ow)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
    else:  # bilinear
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        f = img.astype(np.float32)
        out = (f[y0[:, None], x0[None, :]] * (1 - wy) * (1 - wx)
               + f[y1[:, None], x0[None, :]] * wy * (1 - wx)
               + f[y0[:, None], x1[None, :]] * (1 - wy) * wx
               + f[y1[:, None], x1[None, :]] * wy * wx)
        if img.dtype == np.uint8:
            out = np.clip(out + 0.5, 0, 255).astype(np.uint8)
    return out


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    return crop(img, (h - th) // 2, (w - tw) // 2, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def normalize(img, mean, std, data_format="CHW"):
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def to_tensor(img, data_format="CHW"):
    img = _as_hwc(img)
    arr = img.astype(np.float32)
    if img.dtype == np.uint8:
        arr = arr / 255.0
    if data_format == "CHW":
        arr = arr.transpose(2, 0, 1)
    return arr


# -------------------------------------------------------------- transforms
class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, img):
        for t in self.transforms:
            img = t(img)
        return img


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std, self.data_format = mean, std, data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size, self.interpolation = size, interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


def _pad_spec(padding):
    """Paddle padding semantics → ((top, bottom), (left, right)).
    int: all sides; (l, tb): left/right, top/bottom; (l, t, r, b)."""
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l, t = padding
        r, b = l, t
    else:
        l, t, r, b = padding
    return ((t, b), (l, r))


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.padding = size, padding
        self.pad_if_needed = pad_if_needed

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            (t, b), (l, r) = _pad_spec(self.padding)
            img = np.pad(img, ((t, b), (l, r), (0, 0)))
        th, tw = self.size
        if self.pad_if_needed:
            ph = max(0, th - img.shape[0])
            pw = max(0, tw - img.shape[1])
            if ph or pw:
                img = np.pad(img, ((ph, ph), (pw, pw), (0, 0)))
        h, w = img.shape[:2]
        top = random.randint(0, h - th)
        left = random.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if random.random() < self.prob else img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if random.random() < self.prob else img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        img = _as_hwc(img)
        factor = 1 + random.uniform(-self.value, self.value)
        out = img.astype(np.float32) * factor
        if img.dtype == np.uint8:
            return np.clip(out, 0, 255).astype(np.uint8)
        return out


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        self.padding, self.fill = padding, fill

    def _apply_image(self, img):
        (t, b), (l, r) = _pad_spec(self.padding)
        return np.pad(_as_hwc(img), ((t, b), (l, r), (0, 0)),
                      constant_values=self.fill)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size, self.scale, self.ratio = size, scale, ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            tw = int(round((target * ar) ** 0.5))
            th = int(round((target / ar) ** 0.5))
            if 0 < tw <= w and 0 < th <= h:
                top = random.randint(0, h - th)
                left = random.randint(0, w - tw)
                return resize(crop(img, top, left, th, tw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


# surface part 2 (color ops, warps, erasing)
from .transforms_extra import *  # noqa: E402,F401,F403
from .transforms_extra import adjust_saturation  # noqa: E402,F401
