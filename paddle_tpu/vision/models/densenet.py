"""DenseNet (reference: python/paddle/vision/models/densenet.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264"]

_CFG = {
    121: (6, 12, 24, 16, 32, 64),
    161: (6, 12, 36, 24, 48, 96),
    169: (6, 12, 32, 32, 32, 64),
    201: (6, 12, 48, 32, 32, 64),
    264: (6, 12, 64, 48, 32, 64),
}


class _DenseLayer(nn.Layer):
    def __init__(self, num_channels, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = nn.BatchNorm2D(num_channels)
        self.conv1 = nn.Conv2D(num_channels, bn_size * growth_rate, 1,
                               bias_attr=False)
        self.bn2 = nn.BatchNorm2D(bn_size * growth_rate)
        self.conv2 = nn.Conv2D(bn_size * growth_rate, growth_rate, 3,
                               padding=1, bias_attr=False)
        self.dropout = dropout
        self.relu = nn.ReLU()

    def forward(self, x):
        y = self.conv1(self.relu(self.bn1(x)))
        y = self.conv2(self.relu(self.bn2(y)))
        if self.dropout:
            import paddle_tpu.nn.functional as F
            y = F.dropout(y, p=self.dropout, training=self.training)
        from ...ops.manipulation import concat
        return concat([x, y], axis=1)


class _DenseBlock(nn.Layer):
    def __init__(self, num_layers, num_channels, growth_rate, bn_size,
                 dropout):
        super().__init__()
        self.layers = nn.LayerList([
            _DenseLayer(num_channels + i * growth_rate, growth_rate,
                        bn_size, dropout) for i in range(num_layers)])

    def forward(self, x):
        for layer in self.layers:
            x = layer(x)
        return x


class _Transition(nn.Layer):
    def __init__(self, num_channels, num_out):
        super().__init__()
        self.bn = nn.BatchNorm2D(num_channels)
        self.conv = nn.Conv2D(num_channels, num_out, 1, bias_attr=False)
        self.pool = nn.AvgPool2D(2, stride=2)
        self.relu = nn.ReLU()

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


class DenseNet(nn.Layer):
    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        b1, b2, b3, b4, growth, init_feat = _CFG[layers]
        self.stem = nn.Sequential(
            nn.Conv2D(3, init_feat, 7, stride=2, padding=3,
                      bias_attr=False),
            nn.BatchNorm2D(init_feat), nn.ReLU(),
            nn.MaxPool2D(3, stride=2, padding=1))
        ch = init_feat
        blocks = []
        for i, n in enumerate((b1, b2, b3, b4)):
            blocks.append(_DenseBlock(n, ch, growth, bn_size, dropout))
            ch += n * growth
            if i != 3:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.blocks = nn.Sequential(*blocks)
        self.bn = nn.BatchNorm2D(ch)
        self.relu = nn.ReLU()
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.classifier(x.flatten(1))
        return x


def _densenet(layers, pretrained=False, **kwargs):
    return DenseNet(layers=layers, **kwargs)


def densenet121(pretrained=False, **kwargs):
    return _densenet(121, pretrained, **kwargs)


def densenet161(pretrained=False, **kwargs):
    return _densenet(161, pretrained, **kwargs)


def densenet169(pretrained=False, **kwargs):
    return _densenet(169, pretrained, **kwargs)


def densenet201(pretrained=False, **kwargs):
    return _densenet(201, pretrained, **kwargs)


def densenet264(pretrained=False, **kwargs):
    return _densenet(264, pretrained, **kwargs)
