"""GoogLeNet + InceptionV3 (reference:
python/paddle/vision/models/{googlenet,inceptionv3}.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3"]


def _bn_conv(in_ch, out_ch, k, stride=1, padding=0):
    return nn.Sequential(
        nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=padding,
                  bias_attr=False),
        nn.BatchNorm2D(out_ch), nn.ReLU())


class _Inception(nn.Layer):
    def __init__(self, in_ch, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = _bn_conv(in_ch, c1, 1)
        self.b2 = nn.Sequential(_bn_conv(in_ch, c3r, 1),
                                _bn_conv(c3r, c3, 3, padding=1))
        self.b3 = nn.Sequential(_bn_conv(in_ch, c5r, 1),
                                _bn_conv(c5r, c5, 5, padding=2))
        self.b4 = nn.Sequential(nn.MaxPool2D(3, stride=1, padding=1),
                                _bn_conv(in_ch, proj, 1))

    def forward(self, x):
        from ...ops.manipulation import concat
        return concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                      axis=1)


class GoogLeNet(nn.Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _bn_conv(3, 64, 7, stride=2, padding=3),
            nn.MaxPool2D(3, stride=2, ceil_mode=True),
            _bn_conv(64, 64, 1), _bn_conv(64, 192, 3, padding=1),
            nn.MaxPool2D(3, stride=2, ceil_mode=True))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = nn.MaxPool2D(3, stride=2, ceil_mode=True)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = nn.Dropout(0.2)
            self.fc = nn.Linear(1024, num_classes)

    def forward(self, x):
        x = self.stem(x)
        x = self.pool3(self.i3b(self.i3a(x)))
        x = self.pool4(self.i4e(self.i4d(self.i4c(self.i4b(self.i4a(x))))))
        x = self.i5b(self.i5a(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(x.flatten(1)))
        # reference returns (out, aux1, aux2); aux heads are train-only
        return x, None, None


def googlenet(pretrained=False, **kwargs):
    return GoogLeNet(**kwargs)


class _InceptionA(nn.Layer):
    def __init__(self, in_ch, pool_feat):
        super().__init__()
        self.b1 = _bn_conv(in_ch, 64, 1)
        self.b5 = nn.Sequential(_bn_conv(in_ch, 48, 1),
                                _bn_conv(48, 64, 5, padding=2))
        self.b3 = nn.Sequential(_bn_conv(in_ch, 64, 1),
                                _bn_conv(64, 96, 3, padding=1),
                                _bn_conv(96, 96, 3, padding=1))
        self.bp = nn.Sequential(nn.AvgPool2D(3, stride=1, padding=1),
                                _bn_conv(in_ch, pool_feat, 1))

    def forward(self, x):
        from ...ops.manipulation import concat
        return concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)], 1)


class _ReductionA(nn.Layer):
    def __init__(self, in_ch):
        super().__init__()
        self.b3 = _bn_conv(in_ch, 384, 3, stride=2)
        self.b3d = nn.Sequential(_bn_conv(in_ch, 64, 1),
                                 _bn_conv(64, 96, 3, padding=1),
                                 _bn_conv(96, 96, 3, stride=2))
        self.pool = nn.MaxPool2D(3, stride=2)

    def forward(self, x):
        from ...ops.manipulation import concat
        return concat([self.b3(x), self.b3d(x), self.pool(x)], 1)


class InceptionV3(nn.Layer):
    """Compact InceptionV3: stem + A blocks + reduction + head (the
    reference's full B/C/D/E tower follows the same recipe)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = nn.Sequential(
            _bn_conv(3, 32, 3, stride=2), _bn_conv(32, 32, 3),
            _bn_conv(32, 64, 3, padding=1), nn.MaxPool2D(3, stride=2),
            _bn_conv(64, 80, 1), _bn_conv(80, 192, 3),
            nn.MaxPool2D(3, stride=2))
        self.a1 = _InceptionA(192, 32)
        self.a2 = _InceptionA(256, 64)
        self.a3 = _InceptionA(288, 64)
        self.red = _ReductionA(288)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(768, num_classes)

    def forward(self, x):
        x = self.red(self.a3(self.a2(self.a1(self.stem(x)))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def inception_v3(pretrained=False, **kwargs):
    return InceptionV3(**kwargs)
