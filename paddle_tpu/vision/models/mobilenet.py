"""MobileNet v1/v2 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py)."""
from ... import nn

__all__ = ["MobileNetV1", "MobileNetV2", "mobilenet_v1", "mobilenet_v2"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel=3, stride=1, groups=1,
                 activation=nn.ReLU6):
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride,
                      padding=(kernel - 1) // 2, groups=groups,
                      bias_attr=False),
            nn.BatchNorm2D(out_c), activation())


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def dw_sep(in_c, out_c, stride):
            return nn.Sequential(
                ConvBNReLU(in_c, in_c, 3, stride, groups=in_c,
                           activation=nn.ReLU),
                ConvBNReLU(in_c, out_c, 1, activation=nn.ReLU))

        s = lambda c: int(c * scale)
        self.features = nn.Sequential(
            ConvBNReLU(3, s(32), 3, 2, activation=nn.ReLU),
            dw_sep(s(32), s(64), 1), dw_sep(s(64), s(128), 2),
            dw_sep(s(128), s(128), 1), dw_sep(s(128), s(256), 2),
            dw_sep(s(256), s(256), 1), dw_sep(s(256), s(512), 2),
            *[dw_sep(s(512), s(512), 1) for _ in range(5)],
            dw_sep(s(512), s(1024), 2), dw_sep(s(1024), s(1024), 1))
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, 1))
        layers.extend([
            ConvBNReLU(hidden, hidden, 3, stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup)])
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        return x + self.conv(x) if self.use_res else self.conv(x)


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        in_c = _make_divisible(32 * scale)
        last_c = _make_divisible(1280 * max(1.0, scale))
        feats = [ConvBNReLU(3, in_c, 3, 2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(in_c, out_c,
                                              s if i == 0 else 1, t))
                in_c = out_c
        feats.append(ConvBNReLU(in_c, last_c, 1))
        self.features = nn.Sequential(*feats)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D((1, 1))
        if num_classes > 0:
            self.classifier = nn.Sequential(nn.Dropout(0.2),
                                            nn.Linear(last_c, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)
