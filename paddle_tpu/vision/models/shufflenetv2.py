"""ShuffleNetV2 (reference: python/paddle/vision/models/shufflenetv2.py)."""
from __future__ import annotations

from ... import nn

__all__ = ["ShuffleNetV2", "shufflenet_v2_x0_25", "shufflenet_v2_x0_33",
           "shufflenet_v2_x0_5", "shufflenet_v2_x1_0", "shufflenet_v2_x1_5",
           "shufflenet_v2_x2_0", "shufflenet_v2_swish"]

_STAGE_OUT = {
    "0.25": (24, 24, 48, 96, 512), "0.33": (24, 32, 64, 128, 512),
    "0.5": (24, 48, 96, 192, 1024), "1.0": (24, 116, 232, 464, 1024),
    "1.5": (24, 176, 352, 704, 1024), "2.0": (24, 244, 488, 976, 2048),
}


def _channel_shuffle(x, groups):
    from ...ops.manipulation import reshape, transpose
    b, c, h, w = x.shape
    x = reshape(x, [b, groups, c // groups, h, w])
    x = transpose(x, [0, 2, 1, 3, 4])
    return reshape(x, [b, c, h, w])


def _conv_bn(in_ch, out_ch, k, stride=1, groups=1, act=None):
    layers = [nn.Conv2D(in_ch, out_ch, k, stride=stride, padding=k // 2,
                        groups=groups, bias_attr=False),
              nn.BatchNorm2D(out_ch)]
    if act == "relu":
        layers.append(nn.ReLU())
    elif act == "swish":
        layers.append(nn.Swish())
    return nn.Sequential(*layers)


class _InvertedResidual(nn.Layer):
    def __init__(self, in_ch, out_ch, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = out_ch // 2
        if stride == 2:
            self.branch1 = nn.Sequential(
                _conv_bn(in_ch, in_ch, 3, stride, groups=in_ch),
                _conv_bn(in_ch, branch, 1, act=act))
            in2 = in_ch
        else:
            self.branch1 = None
            in2 = in_ch // 2
        self.branch2 = nn.Sequential(
            _conv_bn(in2, branch, 1, act=act),
            _conv_bn(branch, branch, 3, stride, groups=branch),
            _conv_bn(branch, branch, 1, act=act))

    def forward(self, x):
        from ...ops.manipulation import concat, split
        if self.stride == 1:
            x1, x2 = split(x, 2, axis=1)
            out = concat([x1, self.branch2(x2)], axis=1)
        else:
            out = concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(nn.Layer):
    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        outs = _STAGE_OUT[str(scale) if str(scale) in _STAGE_OUT
                          else f"{scale:.2g}"]
        self.conv1 = _conv_bn(3, outs[0], 3, stride=2, act=act)
        self.pool1 = nn.MaxPool2D(3, stride=2, padding=1)
        stages = []
        in_ch = outs[0]
        for i, reps in enumerate((4, 8, 4)):
            out_ch = outs[i + 1]
            blocks = [_InvertedResidual(in_ch, out_ch, 2, act)]
            blocks += [_InvertedResidual(out_ch, out_ch, 1, act)
                       for _ in range(reps - 1)]
            stages.append(nn.Sequential(*blocks))
            in_ch = out_ch
        self.stages = nn.Sequential(*stages)
        self.conv5 = _conv_bn(in_ch, outs[4], 1, act=act)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(outs[4], num_classes)

    def forward(self, x):
        x = self.conv5(self.stages(self.pool1(self.conv1(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2("0.25", **kw)


def shufflenet_v2_x0_33(pretrained=False, **kw):
    return ShuffleNetV2("0.33", **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2("0.5", **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2("1.0", **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2("1.5", **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2("2.0", **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2("1.0", act="swish", **kw)
