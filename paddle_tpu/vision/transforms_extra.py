"""Vision transforms part 2 (reference: python/paddle/vision/transforms/
{transforms,functional}.py — color ops, geometric warps, erasing).
Numpy HWC bodies like the rest of the module; warps use inverse-map
bilinear sampling."""
from __future__ import annotations

import math
import numbers
import random

import numpy as np

from .transforms import BaseTransform, _as_hwc, _pad_spec

__all__ = [
    "adjust_brightness", "adjust_contrast", "adjust_hue", "to_grayscale",
    "rotate", "affine", "perspective", "erase", "pad",
    "ColorJitter", "ContrastTransform", "SaturationTransform",
    "HueTransform", "Grayscale", "RandomRotation", "RandomAffine",
    "RandomPerspective", "RandomErasing",
]


def _restore_dtype(out, ref):
    if ref.dtype == np.uint8:
        return np.clip(out, 0, 255).astype(np.uint8)
    return out.astype(ref.dtype)


def adjust_brightness(img, brightness_factor):
    img = _as_hwc(img)
    return _restore_dtype(img.astype(np.float32) * brightness_factor, img)


def adjust_contrast(img, contrast_factor):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    gray_mean = (f @ np.array([0.299, 0.587, 0.114], np.float32)).mean() \
        if img.shape[-1] == 3 else f.mean()
    return _restore_dtype(gray_mean + contrast_factor * (f - gray_mean), img)


def adjust_saturation(img, saturation_factor):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    gray = f @ np.array([0.299, 0.587, 0.114], np.float32)
    gray = gray[..., None]
    return _restore_dtype(gray + saturation_factor * (f - gray), img)


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor (in [-0.5, 0.5]) via HSV roundtrip
    (reference transforms/functional_cv2.py adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    img = _as_hwc(img)
    f = img.astype(np.float32) / (255.0 if img.dtype == np.uint8 else 1.0)
    r, g, b = f[..., 0], f[..., 1], f[..., 2]
    maxc = f.max(-1)
    minc = f.min(-1)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc = (maxc - r) / dz
    gc = (maxc - g) / dz
    bc = (maxc - b) / dz
    h = np.where(r == maxc, bc - gc,
                 np.where(g == maxc, 2.0 + rc - bc, 4.0 + gc - rc))
    h = (h / 6.0) % 1.0
    h = np.where(delta == 0, 0.0, h)
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6.0)
    fr = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * fr)
    t = v * (1.0 - s * (1.0 - fr))
    i = i.astype(np.int32) % 6
    r2 = np.choose(i, [v, q, p, p, t, v])
    g2 = np.choose(i, [t, v, v, q, p, p])
    b2 = np.choose(i, [p, p, t, v, v, q])
    out = np.stack([r2, g2, b2], axis=-1)
    if img.dtype == np.uint8:
        out = out * 255.0
    return _restore_dtype(out, img)


def to_grayscale(img, num_output_channels=1):
    img = _as_hwc(img)
    f = img.astype(np.float32)
    gray = f @ np.array([0.299, 0.587, 0.114], np.float32) \
        if img.shape[-1] == 3 else f[..., 0]
    out = np.repeat(gray[..., None], num_output_channels, axis=-1)
    return _restore_dtype(out, img)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _as_hwc(img)
    (t, b), (l, r) = _pad_spec(padding)
    if padding_mode == "constant":
        return np.pad(img, ((t, b), (l, r), (0, 0)), constant_values=fill)
    mode = {"edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    return np.pad(img, ((t, b), (l, r), (0, 0)), mode=mode)


def _inverse_warp(img, inv_matrix, out_shape=None, interpolation="bilinear",
                  fill=0):
    """Sample img at inv_matrix @ [x_out, y_out, 1] (3x3 projective)."""
    img = _as_hwc(img)
    h, w = img.shape[:2]
    oh, ow = out_shape or (h, w)
    ys, xs = np.meshgrid(np.arange(oh, dtype=np.float32),
                         np.arange(ow, dtype=np.float32), indexing="ij")
    ones = np.ones_like(xs)
    pts = np.stack([xs, ys, ones], axis=0).reshape(3, -1)
    src = inv_matrix @ pts
    sx = src[0] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    sy = src[1] / np.maximum(np.abs(src[2]), 1e-9) * np.sign(src[2])
    sx = sx.reshape(oh, ow)
    sy = sy.reshape(oh, ow)
    f = img.astype(np.float32)
    if interpolation == "nearest":
        xi = np.round(sx).astype(np.int64)
        yi = np.round(sy).astype(np.int64)
        valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
        out = np.full((oh, ow, img.shape[2]), float(fill), np.float32)
        out[valid] = f[yi[valid], xi[valid]]
    else:
        x0 = np.floor(sx).astype(np.int64)
        y0 = np.floor(sy).astype(np.int64)
        wx = (sx - x0)[..., None]
        wy = (sy - y0)[..., None]
        out = np.zeros((oh, ow, img.shape[2]), np.float32)
        weight_sum = np.zeros((oh, ow, 1), np.float32)
        for dy, wgt_y in ((0, 1 - wy), (1, wy)):
            for dx, wgt_x in ((0, 1 - wx), (1, wx)):
                xi = x0 + dx
                yi = y0 + dy
                valid = (xi >= 0) & (xi < w) & (yi >= 0) & (yi < h)
                wgt = wgt_y * wgt_x
                vals = np.zeros_like(out)
                vals[valid] = f[yi[valid], xi[valid]]
                out += vals * wgt * valid[..., None]
                weight_sum += wgt * valid[..., None]
        fillv = np.float32(fill)
        out = np.where(weight_sum > 1e-6,
                       out + fillv * (1 - weight_sum), fillv)
    return _restore_dtype(out, img)


def _affine_inv_matrix(angle, translate, scale, shear, center):
    # positive angle = counter-clockwise on screen; array coords have y
    # down, so negate (PIL/torchvision convention)
    cx, cy = center
    rot = math.radians(-angle)
    sx = math.radians(shear[0])
    sy = math.radians(shear[1])
    # forward: T(center) R S Shear T(-center) T(translate)
    a = math.cos(rot - sy) / math.cos(sy)
    b = -math.cos(rot - sy) * math.tan(sx) / math.cos(sy) - math.sin(rot)
    c = math.sin(rot - sy) / math.cos(sy)
    d = -math.sin(rot - sy) * math.tan(sx) / math.cos(sy) + math.cos(rot)
    m = np.array([[a, b, 0.0], [c, d, 0.0], [0.0, 0.0, 1.0]], np.float64)
    m[:2, :2] *= scale
    fwd = (np.array([[1, 0, cx + translate[0]], [0, 1, cy + translate[1]],
                     [0, 0, 1]], np.float64)
           @ m
           @ np.array([[1, 0, -cx], [0, 1, -cy], [0, 0, 1]], np.float64))
    return np.linalg.inv(fwd)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    img_np = _as_hwc(img)
    h, w = img_np.shape[:2]
    ctr = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    out_shape = None
    if expand:
        rad = math.radians(angle)
        nw = int(abs(w * math.cos(rad)) + abs(h * math.sin(rad)) + 0.5)
        nh = int(abs(h * math.cos(rad)) + abs(w * math.sin(rad)) + 0.5)
        out_shape = (nh, nw)
        inv = _affine_inv_matrix(angle, (0, 0), 1.0, (0.0, 0.0), ctr)
        # shift so the rotated content is centered in the expanded canvas
        shift = np.array([[1, 0, (w - nw) / 2.0], [0, 1, (h - nh) / 2.0],
                          [0, 0, 1]], np.float64)
        inv = inv @ shift
    else:
        inv = _affine_inv_matrix(angle, (0, 0), 1.0, (0.0, 0.0), ctr)
    return _inverse_warp(img_np, inv, out_shape, interpolation, fill)


def affine(img, angle, translate, scale, shear, interpolation="nearest",
           fill=0, center=None):
    img_np = _as_hwc(img)
    h, w = img_np.shape[:2]
    if isinstance(shear, numbers.Number):
        shear = (shear, 0.0)
    ctr = center or ((w - 1) * 0.5, (h - 1) * 0.5)
    inv = _affine_inv_matrix(angle, tuple(translate), scale, tuple(shear),
                             ctr)
    return _inverse_warp(img_np, inv, None, interpolation, fill)


def _perspective_coeffs(startpoints, endpoints):
    a = []
    bvec = []
    for (sx, sy), (ex, ey) in zip(startpoints, endpoints):
        a.append([ex, ey, 1, 0, 0, 0, -sx * ex, -sx * ey])
        a.append([0, 0, 0, ex, ey, 1, -sy * ex, -sy * ey])
        bvec += [sx, sy]
    coef = np.linalg.solve(np.asarray(a, np.float64),
                           np.asarray(bvec, np.float64))
    return np.concatenate([coef, [1.0]]).reshape(3, 3)


def perspective(img, startpoints, endpoints, interpolation="nearest",
                fill=0):
    """Warp mapping startpoints -> endpoints (reference
    transforms/functional.py perspective)."""
    inv = _perspective_coeffs(startpoints, endpoints)
    return _inverse_warp(_as_hwc(img), inv, None, interpolation, fill)


def erase(img, i, j, h, w, v, inplace=False):
    """Erase a region with value v (reference transforms/functional.py
    erase).  Accepts HWC/CHW numpy or Tensor."""
    from ..framework.tensor import Tensor
    if isinstance(img, Tensor):
        arr = np.asarray(img.numpy()).copy()
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) \
            and arr.shape[0] <= arr.shape[2]
        if chw:
            arr[:, i:i + h, j:j + w] = v
        else:
            arr[i:i + h, j:j + w] = v
        import paddle_tpu
        return paddle_tpu.to_tensor(arr)
    arr = img if inplace else img.copy()
    if arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[0] <= \
            arr.shape[2]:
        arr[:, i:i + h, j:j + w] = v
    else:
        arr[i:i + h, j:j + w] = v
    return arr


# ---------------------------------------------------------------- classes

class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("contrast value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        f = 1 + random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if value < 0:
            raise ValueError("saturation value should be non-negative")
        self.value = value

    def _apply_image(self, img):
        f = 1 + random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value should be in [0, 0.5]")
        self.value = value

    def _apply_image(self, img):
        return adjust_hue(img, random.uniform(-self.value, self.value))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        self.brightness = brightness
        self.contrast = contrast
        self.saturation = saturation
        self.hue = hue

    def _apply_image(self, img):
        ops = []
        if self.brightness:
            ops.append(lambda im: adjust_brightness(
                im, 1 + random.uniform(-self.brightness, self.brightness)))
        if self.contrast:
            ops.append(lambda im: adjust_contrast(
                im, 1 + random.uniform(-self.contrast, self.contrast)))
        if self.saturation:
            ops.append(lambda im: adjust_saturation(
                im, 1 + random.uniform(-self.saturation, self.saturation)))
        if self.hue:
            ops.append(lambda im: adjust_hue(
                im, random.uniform(-self.hue, self.hue)))
        random.shuffle(ops)
        for op in ops:
            img = op(img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return to_grayscale(img, self.num_output_channels)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.args = (interpolation, expand, center, fill)

    def _apply_image(self, img):
        interp, expand, center, fill = self.args
        angle = random.uniform(*self.degrees)
        return rotate(img, angle, interp, expand, center, fill)


class RandomAffine(BaseTransform):
    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.translate = translate
        self.scale = scale
        self.shear = shear
        self.args = (interpolation, fill, center)

    def _apply_image(self, img):
        interp, fill, center = self.args
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        angle = random.uniform(*self.degrees)
        tx = ty = 0.0
        if self.translate:
            tx = random.uniform(-self.translate[0], self.translate[0]) * w
            ty = random.uniform(-self.translate[1], self.translate[1]) * h
        sc = random.uniform(*self.scale) if self.scale else 1.0
        sh = (random.uniform(-self.shear, self.shear), 0.0) \
            if isinstance(self.shear, numbers.Number) else \
            ((random.uniform(*self.shear[:2]),
              random.uniform(*self.shear[2:]) if len(self.shear) == 4
              else 0.0) if self.shear else (0.0, 0.0))
        return affine(arr, angle, (tx, ty), sc, sh, interp, fill, center)


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        self.prob = prob
        self.distortion_scale = distortion_scale
        self.args = (interpolation, fill)

    def _apply_image(self, img):
        interp, fill = self.args
        if random.random() >= self.prob:
            return _as_hwc(img)
        arr = _as_hwc(img)
        h, w = arr.shape[:2]
        d = self.distortion_scale
        half_h = int(h * d / 2)
        half_w = int(w * d / 2)
        start = [(0, 0), (w - 1, 0), (w - 1, h - 1), (0, h - 1)]
        end = [(random.randint(0, half_w), random.randint(0, half_h)),
               (w - 1 - random.randint(0, half_w),
                random.randint(0, half_h)),
               (w - 1 - random.randint(0, half_w),
                h - 1 - random.randint(0, half_h)),
               (random.randint(0, half_w),
                h - 1 - random.randint(0, half_h))]
        return perspective(arr, start, end, interp, fill)


class RandomErasing(BaseTransform):
    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value
        self.inplace = inplace

    def _apply_image(self, img):
        if random.random() >= self.prob:
            return img
        from ..framework.tensor import Tensor
        arr = np.asarray(img.numpy()) if isinstance(img, Tensor) else img
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[0] <= arr.shape[2]
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = random.uniform(*self.scale) * area
            ar = random.uniform(*self.ratio)
            eh = int(round(math.sqrt(target / ar)))
            ew = int(round(math.sqrt(target * ar)))
            if eh < h and ew < w:
                i = random.randint(0, h - eh)
                j = random.randint(0, w - ew)
                return erase(img, i, j, eh, ew, self.value, self.inplace)
        return img
