"""paddle.vision equivalent (reference: python/paddle/vision — models,
transforms, datasets; 15.8k LoC)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401


_image_backend = "pil"


def set_image_backend(backend):
    """(reference vision/image.py set_image_backend)"""
    global _image_backend
    if backend not in ("pil", "cv2", "tensor"):
        raise ValueError(f"unsupported backend {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """Load an image file (reference vision/image.py image_load)."""
    backend = backend or _image_backend
    if backend == "cv2":
        try:
            import cv2
            return cv2.imread(path)
        except ImportError as e:
            raise RuntimeError("cv2 backend needs opencv installed") from e
    from PIL import Image
    img = Image.open(path)
    if backend == "tensor":
        import numpy as np
        from ..framework.tensor import Tensor
        import jax.numpy as jnp
        return Tensor(jnp.asarray(np.asarray(img)))
    return img
