"""paddle.geometric — graph message passing + segment ops.

Reference: python/paddle/geometric/ (message_passing/send_recv.py
send_u_recv/send_ue_recv, math.py segment_sum/mean/max/min; kernels
paddle/phi/kernels/*/graph_send_recv_kernel.*, segment_pool_kernel.*).

TPU formulation: all of these are jax segment reductions
(jax.ops.segment_*) — static num_segments keeps them jit-compatible, and
XLA lowers scatter-reduce natively.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import op

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]


def _num_segments(count, x):
    if count is None:
        raise ValueError(
            "out_size/num_segments must be given under TPU/XLA: dynamic "
            "segment counts would make shapes data-dependent (pass "
            "out_size=<num nodes>)")
    return int(count)


@op
def segment_sum(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


@op
def segment_mean(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    tot = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids, jnp.float32),
                              segment_ids, num_segments=n)
    return tot / jnp.maximum(cnt, 1.0)[
        (...,) + (None,) * (data.ndim - 1)]


@op
def segment_max(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_max(data, segment_ids, num_segments=n)


@op
def segment_min(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_min(data, segment_ids, num_segments=n)


_POOLS = {"sum": segment_sum, "add": segment_sum, "mean": segment_mean,
          "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """Gather x[src] then segment-reduce onto dst (reference:
    send_recv.py send_u_recv)."""
    from ..ops.manipulation import gather
    msgs = gather(x, src_index)
    if out_size is None:
        out_size = x.shape[0]
    return _POOLS[reduce_op](msgs, dst_index, num_segments=out_size)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """Node ⊕ edge messages then reduce (reference: send_ue_recv)."""
    from ..ops.manipulation import gather
    from ..ops import math as M
    msgs = gather(x, src_index)
    combine = {"add": M.add, "sub": M.subtract, "mul": M.multiply,
               "div": M.divide}[message_op]
    msgs = combine(msgs, y)
    if out_size is None:
        out_size = x.shape[0]
    return _POOLS[reduce_op](msgs, dst_index, num_segments=out_size)
