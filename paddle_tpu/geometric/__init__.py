"""paddle.geometric — graph message passing + segment ops.

Reference: python/paddle/geometric/ (message_passing/send_recv.py
send_u_recv/send_ue_recv, math.py segment_sum/mean/max/min; kernels
paddle/phi/kernels/*/graph_send_recv_kernel.*, segment_pool_kernel.*).

TPU formulation: all of these are jax segment reductions
(jax.ops.segment_*) — static num_segments keeps them jit-compatible, and
XLA lowers scatter-reduce natively.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.registry import op

__all__ = ["send_u_recv", "send_ue_recv", "segment_sum", "segment_mean",
           "segment_max", "segment_min"]


def _num_segments(count, x):
    if count is None:
        raise ValueError(
            "out_size/num_segments must be given under TPU/XLA: dynamic "
            "segment counts would make shapes data-dependent (pass "
            "out_size=<num nodes>)")
    return int(count)


@op
def segment_sum(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_sum(data, segment_ids, num_segments=n)


@op
def segment_mean(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    tot = jax.ops.segment_sum(data, segment_ids, num_segments=n)
    cnt = jax.ops.segment_sum(jnp.ones_like(segment_ids, jnp.float32),
                              segment_ids, num_segments=n)
    return tot / jnp.maximum(cnt, 1.0)[
        (...,) + (None,) * (data.ndim - 1)]


@op
def segment_max(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_max(data, segment_ids, num_segments=n)


@op
def segment_min(data, segment_ids, num_segments=None):
    n = _num_segments(num_segments, data)
    return jax.ops.segment_min(data, segment_ids, num_segments=n)


_POOLS = {"sum": segment_sum, "add": segment_sum, "mean": segment_mean,
          "max": segment_max, "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None):
    """Gather x[src] then segment-reduce onto dst (reference:
    send_recv.py send_u_recv)."""
    from ..ops.manipulation import gather
    msgs = gather(x, src_index)
    if out_size is None:
        out_size = x.shape[0]
    return _POOLS[reduce_op](msgs, dst_index, num_segments=out_size)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None):
    """Node ⊕ edge messages then reduce (reference: send_ue_recv)."""
    from ..ops.manipulation import gather
    from ..ops import math as M
    msgs = gather(x, src_index)
    combine = {"add": M.add, "sub": M.subtract, "mul": M.multiply,
               "div": M.divide}[message_op]
    msgs = combine(msgs, y)
    if out_size is None:
        out_size = x.shape[0]
    return _POOLS[reduce_op](msgs, dst_index, num_segments=out_size)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex node ids to a compact range (reference geometric/
    reindex.py reindex_graph).  Host-side — eager only."""
    import numpy as np
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    xs = np.asarray(x.numpy() if hasattr(x, "numpy") else x).reshape(-1)
    nb = np.asarray(neighbors.numpy() if hasattr(neighbors, "numpy")
                    else neighbors).reshape(-1)
    nodes = np.concatenate([xs, nb])
    uniq, idx = np.unique(nodes, return_index=True)
    order = nodes[np.sort(idx)]  # first-seen order (x first)
    remap = {int(v): i for i, v in enumerate(order)}
    reindex_src = np.asarray([remap[int(v)] for v in nb], np.int64)
    cnt = np.asarray(count.numpy() if hasattr(count, "numpy")
                     else count).reshape(-1)
    reindex_dst = np.repeat(np.arange(len(xs), dtype=np.int64), cnt)
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(order)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous variant: neighbors/count are per-edge-type lists."""
    import numpy as np
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    nbs = [np.asarray(n.numpy() if hasattr(n, "numpy") else n).reshape(-1)
           for n in neighbors]
    cnts = [np.asarray(c.numpy() if hasattr(c, "numpy") else c).reshape(-1)
            for c in count]
    merged_n = np.concatenate(nbs)
    xs = np.asarray(x.numpy() if hasattr(x, "numpy") else x).reshape(-1)
    nodes = np.concatenate([xs, merged_n])
    _, idx = np.unique(nodes, return_index=True)
    order = nodes[np.sort(idx)]
    remap = {int(v): i for i, v in enumerate(order)}
    srcs, dsts = [], []
    for nb, cnt in zip(nbs, cnts):
        srcs.append(np.asarray([remap[int(v)] for v in nb], np.int64))
        dsts.append(np.repeat(np.arange(len(xs), dtype=np.int64), cnt))
    return (Tensor(jnp.asarray(np.concatenate(srcs))),
            Tensor(jnp.asarray(np.concatenate(dsts))),
            Tensor(jnp.asarray(order)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over CSC (reference geometric/
    sampling/neighbors.py sample_neighbors).  Host-side — eager only."""
    import numpy as np
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    rows = np.asarray(row.numpy() if hasattr(row, "numpy") else row)
    cptr = np.asarray(colptr.numpy() if hasattr(colptr, "numpy")
                      else colptr)
    nodes = np.asarray(input_nodes.numpy() if hasattr(input_nodes, "numpy")
                       else input_nodes).reshape(-1)
    rng = np.random.default_rng()
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        beg, end = int(cptr[n]), int(cptr[n + 1])
        neigh = rows[beg:end]
        eid = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            sel = rng.choice(len(neigh), size=sample_size, replace=False)
            neigh = neigh[sel]
            eid = eid[sel]
        out_n.append(neigh)
        out_e.append(eid)
        out_c.append(len(neigh))
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n)
                                   if out_n else np.zeros(0, np.int64)))
    counts = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        return neighbors, counts, Tensor(jnp.asarray(
            np.concatenate(out_e) if out_e else np.zeros(0, np.int64)))
    return neighbors, counts


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weighted variant (reference geometric/sampling/neighbors.py
    weighted_sample_neighbors)."""
    import numpy as np
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    rows = np.asarray(row.numpy() if hasattr(row, "numpy") else row)
    cptr = np.asarray(colptr.numpy() if hasattr(colptr, "numpy")
                      else colptr)
    w = np.asarray(edge_weight.numpy() if hasattr(edge_weight, "numpy")
                   else edge_weight).reshape(-1)
    nodes = np.asarray(input_nodes.numpy() if hasattr(input_nodes, "numpy")
                       else input_nodes).reshape(-1)
    rng = np.random.default_rng()
    out_n, out_c, out_e = [], [], []
    for n in nodes:
        beg, end = int(cptr[n]), int(cptr[n + 1])
        neigh = rows[beg:end]
        eid = np.arange(beg, end)
        if 0 <= sample_size < len(neigh):
            p = w[beg:end].astype(np.float64)
            nonzero = int((p > 0).sum())
            if p.sum() > 0 and nonzero >= sample_size:
                p = p / p.sum()
                sel = rng.choice(len(neigh), size=sample_size,
                                 replace=False, p=p)
            elif p.sum() > 0:
                # fewer positively-weighted neighbors than requested:
                # take every weighted one, fill the rest uniformly
                weighted = np.flatnonzero(p > 0)
                rest = np.flatnonzero(p <= 0)
                fill = rng.choice(rest, size=sample_size - nonzero,
                                  replace=False)
                sel = np.concatenate([weighted, fill])
            else:
                sel = rng.choice(len(neigh), size=sample_size,
                                 replace=False)
            neigh = neigh[sel]
            eid = eid[sel]
        out_n.append(neigh)
        out_e.append(eid)
        out_c.append(len(neigh))
    neighbors = Tensor(jnp.asarray(np.concatenate(out_n)
                                   if out_n else np.zeros(0, np.int64)))
    counts = Tensor(jnp.asarray(np.asarray(out_c, np.int32)))
    if return_eids:
        return neighbors, counts, Tensor(jnp.asarray(
            np.concatenate(out_e) if out_e else np.zeros(0, np.int64)))
    return neighbors, counts


def send_uv(x, y, src_index, dst_index, compute_type="add", name=None):
    """Per-edge message from both endpoints (reference geometric/
    message_passing/send_recv.py send_uv)."""
    import jax.numpy as jnp
    from ..ops.registry import apply_op

    def body(xx, yy, si, di):
        xs = xx[si]
        ys = yy[di]
        if compute_type in ("add",):
            return xs + ys
        if compute_type == "sub":
            return xs - ys
        if compute_type == "mul":
            return xs * ys
        if compute_type == "div":
            return xs / ys
        raise ValueError(f"unknown compute_type {compute_type!r}")

    return apply_op("send_uv", body, (x, y, src_index, dst_index), {})


__all__ += ["reindex_graph", "reindex_heter_graph", "sample_neighbors",
            "weighted_sample_neighbors", "send_uv"]
