"""paddle_tpu: a TPU-native deep-learning framework with the PaddlePaddle
API surface, built on jax/XLA/Pallas.

Architecture (vs. reference /root/reference, see SURVEY.md §8):
  * Tensor        = handle over jax.Array (framework/tensor.py)
  * autograd      = tape over jax.vjp (autograd/tape.py)
  * op layer      = one registry of pure-jax bodies (ops/)
  * static graph  = jax.jit tracing (jit/), StableHLO export
  * distributed   = jax.sharding.Mesh + GSPMD (distributed/)
  * hot kernels   = Pallas TPU (ops/pallas/)
"""
from __future__ import annotations

import jax as _jax

# Paddle's default integer dtype is int64 (python/paddle/tensor/creation.py
# to_tensor); jax's x32 mode would silently truncate. Enable x64 — the
# framework's own creation logic keeps float defaults at float32/bfloat16,
# so TPU matmuls stay on the MXU.
_jax.config.update("jax_enable_x64", True)

from . import jax_compat as _jax_compat
_jax_compat.install()

# -- core types ------------------------------------------------------------
from .framework import dtype as _dtype_mod
from .framework.dtype import (  # noqa: F401
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, DType)
bool = bool_  # paddle.bool
from .framework.tensor import Tensor, to_tensor, is_tensor  # noqa: F401
from .framework import tensor_methods as _tensor_methods  # noqa: F401  (patches Tensor)
from .framework.random import seed, get_rng_state, set_rng_state  # noqa: F401

# -- autograd --------------------------------------------------------------
from .autograd import no_grad, enable_grad, is_grad_enabled, \
    set_grad_enabled, grad  # noqa: F401
from . import autograd  # noqa: F401

# -- ops into the flat namespace ------------------------------------------
from .ops.math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,
    matmul, scale, neg, abs, exp, expm1, log, log2, log10, log1p, sqrt,
    rsqrt, square, sin, cos, tan, asin, acos, atan, sinh, cosh, tanh, asinh,
    acosh, atanh, erf, erfinv, floor, ceil, round, trunc, sign, reciprocal,
    sigmoid, digamma, lgamma, i0, frac, deg2rad, rad2deg, angle, conj, real,
    imag, clip, maximum, minimum, fmax, fmin, atan2, hypot, lerp, stanh,
    logit, multiplex, isnan, isinf, isfinite, nan_to_num, cumsum, cumprod,
    cummax, cummin, logcumsumexp, addmm, inner, outer, heaviside, gcd, lcm,
    diff, trace, kron, cross, dot, polygamma)
from .ops.reduction import (  # noqa: F401
    mean, amax, amin, prod, var, std, nansum, nanmean, count_nonzero,
    logsumexp, argmax, argmin, median, nanmedian, quantile, kthvalue, mode)
from .ops.reduction import sum_ as sum, max_ as max, min_ as min, \
    all_ as all, any_ as any  # noqa: F401
from .ops.manipulation import (  # noqa: F401
    reshape, transpose, concat, stack, unstack, split, chunk, squeeze,
    unsqueeze, flatten, tile, expand, expand_as, broadcast_to,
    broadcast_tensors, gather, gather_nd, scatter, scatter_nd_add,
    scatter_nd, index_select, index_sample, index_add, index_put,
    take_along_axis, put_along_axis, flip, roll, rot90, where, nonzero,
    masked_select, masked_fill, topk, sort, argsort, searchsorted, bucketize,
    unique, unique_consecutive, one_hot, tril, triu, tril_indices,
    triu_indices, diag, diagflat, diagonal, diag_embed, meshgrid, cast, pad,
    repeat_interleave, as_strided, moveaxis, swapaxes, atleast_1d,
    atleast_2d, atleast_3d, view, unfold, tensordot, crop, slice,
    strided_slice, numel, shape, increment, assign, bincount, histogram)
from .ops.manipulation import unstack as unbind  # noqa: F401
from .ops.creation import (  # noqa: F401
    zeros, ones, full, empty, zeros_like, ones_like, full_like, empty_like,
    arange, linspace, logspace, eye, clone, complex, polar, rand, randn,
    uniform, normal, gaussian, randint, randint_like, randperm, multinomial,
    bernoulli, poisson, standard_normal, standard_gamma)
from .ops.linalg import (  # noqa: F401
    mm, bmm, mv, t, einsum, norm, dist, cholesky, cholesky_solve, qr, svd,
    pinv, det, slogdet, solve, triangular_solve, lstsq, lu, eig, eigh,
    eigvals, eigvalsh, matrix_power, matrix_rank, corrcoef, cov,
    histogramdd, bitwise_and, bitwise_or, bitwise_xor, bitwise_not,
    bitwise_left_shift, bitwise_right_shift)
from .ops.linalg import inv as inverse  # noqa: F401
from .ops.comparison import (  # noqa: F401
    equal, not_equal, greater_than, greater_equal, less_than, less_equal,
    equal_all, allclose, isclose, logical_and, logical_or, logical_xor,
    logical_not, is_empty)
from .ops.math_extra import (  # noqa: F401
    logaddexp, copysign, ldexp, nextafter, signbit, sinc, frexp, gammaln,
    gammainc, gammaincc, multigammaln, i0e, i1, i1e, sgn, isneginf,
    isposinf, isreal, isin, take, trapezoid, cumulative_trapezoid, vander,
    renorm, nanquantile, histogram_bin_edges, floor_mod, reduce_as, add_n,
    cdist, pdist, hsplit, vsplit, dsplit, tensor_split, hstack, vstack,
    dstack, row_stack, column_stack, block_diag, cartesian_prod,
    combinations, diagonal_scatter, select_scatter, slice_scatter,
    masked_scatter, index_fill, reverse, unflatten, view_as, as_complex,
    as_real, rank, broadcast_shape, shard_index, log_normal, binomial,
    is_complex, is_floating_point, is_integer)

# -- subpackages -----------------------------------------------------------
from . import ops  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import linalg  # noqa: F401  (namespace module below)
from . import framework  # noqa: F401
from .framework.io import save, load  # noqa: F401
from . import jit  # noqa: F401
from . import device  # noqa: F401
from .device import set_device, get_device, CPUPlace, CUDAPlace, \
    CUDAPinnedPlace, XPUPlace, TPUPlace  # noqa: F401
from . import flags as _flags_mod
from .flags import set_flags, get_flags  # noqa: F401
from . import vision  # noqa: F401
from . import models  # noqa: F401
from . import metric  # noqa: F401
from . import observability  # noqa: F401
from . import profiler  # noqa: F401
from .hapi import Model, summary  # noqa: F401
from . import hapi  # noqa: F401
from . import distribution  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import sparse  # noqa: F401
from . import static  # noqa: F401
from . import inference  # noqa: F401
from . import quantization  # noqa: F401
from . import audio  # noqa: F401
from . import text  # noqa: F401
from . import strings  # noqa: F401
from . import geometric  # noqa: F401
from . import incubate  # noqa: F401
from . import utils  # noqa: F401
from . import onnx  # noqa: F401
from . import version  # noqa: F401
from . import regularizer  # noqa: F401


# -- surface part 2: misc top-level API -----------------------------------
from .framework.dtype import dtype, float8_e4m3fn, float8_e5m2  # noqa: F401
from .nn.layer import ParamAttr  # noqa: F401
from .distributed.fleet.meta_parallel.parallel_wrappers import \
    DataParallel  # noqa: F401
from .framework.random import (  # noqa: F401
    get_rng_state as get_cuda_rng_state, set_rng_state as set_cuda_rng_state)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Printing options for Tensor repr (reference
    python/paddle/tensor/to_string.py:38); maps onto numpy printoptions."""
    import numpy as np
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


class LazyGuard:
    """Parameter-init deferral scope (reference python/paddle/nn/initializer/
    lazy_init.py).  Initialization here is cheap jax host arrays, so the
    guard is a no-op context kept for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from .nn.layer import Layer
    helper = Layer()
    p = helper.create_parameter(shape, attr=attr, dtype=dtype,
                                is_bias=is_bias,
                                default_initializer=default_initializer)
    if name:
        p.name = name
    return p


def batch(reader, batch_size, drop_last=False):
    """Batch a sample reader into a batched reader (legacy fluid API,
    reference python/paddle/reader/decorator.py)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Estimate FLOPs of a network at the given input size (reference
    python/paddle/hapi/dynamic_flops.py): conv/linear dominate; counts
    multiply-adds as 2 ops like the reference."""
    from . import nn as _nn
    x = zeros(input_size, dtype="float32")
    counts = [0]

    def make_post(layer):
        def post(lyr, inputs, outputs):
            import numpy as _np
            out_shape = getattr(outputs, "shape", None)
            if custom_ops and type(lyr) in custom_ops:  # replaces builtin
                counts[0] += int(custom_ops[type(lyr)](lyr, inputs, outputs))
            elif isinstance(lyr, _nn.Linear):
                counts[0] += 2 * int(_np.prod(out_shape)) * \
                    lyr.weight.shape[0]
            elif isinstance(lyr, (_nn.Conv1D, _nn.Conv2D, _nn.Conv3D)):
                w = lyr.weight
                kernel_ops = int(_np.prod(w.shape[1:]))
                counts[0] += 2 * int(_np.prod(out_shape)) * kernel_ops
        return post

    handles = []
    for lyr in net.sublayers(include_self=True):
        handles.append(lyr.register_forward_post_hook(make_post(lyr)))
    was_training = net.training
    net.eval()
    net(x)
    if was_training:
        net.train()
    for h in handles:
        h.remove()
    if print_detail:
        print(f"Total FLOPs: {counts[0]}")
    return counts[0]


def check_shape(shape):
    """Validate a shape argument (reference python/paddle/utils/
    layers_utils.py:474)."""
    if isinstance(shape, (list, tuple)):
        for s in shape:
            if isinstance(s, int) and s < -1:
                raise ValueError(f"invalid dim {s} in shape {shape}")
    return shape


def tolist(x):
    """Return the tensor data as (nested) python lists (reference
    python/paddle/tensor/to_string.py tolist)."""
    return x.tolist()


def disable_signal_handler():
    """Paddle installs C++ signal handlers; there are none here (jax/XLA
    runtime) so this is a documented no-op."""


def iinfo(dtype):
    import numpy as np
    from .framework.dtype import to_np_dtype
    return np.iinfo(to_np_dtype(dtype))


def finfo(dtype):
    import ml_dtypes
    from .framework.dtype import to_np_dtype
    return ml_dtypes.finfo(to_np_dtype(dtype))

__version__ = "0.1.0"

# paddle.disable_static / enable_static compat: this framework is always
# "dygraph" at the API level; jit.to_static provides the compiled path.
_static_mode = False


def disable_static(place=None):
    global _static_mode
    _static_mode = False


def enable_static():
    """Switch to static-graph building (paddle.static.*); ops applied to
    static Variables record a Program DAG instead of executing."""
    global _static_mode
    _static_mode = True


def in_dynamic_mode():
    return not _static_mode


def in_dynamic_or_pir_mode():
    return True


def is_compiled_with_cuda():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_cinn():
    return False


def is_compiled_with_distribute():
    return True


def is_compiled_with_tpu():
    return True


def get_default_dtype():
    return _dtype_mod.dtype(_default_dtype[0])


def set_default_dtype(d):
    _default_dtype[0] = _dtype_mod.dtype(d).name


_default_dtype = ["float32"]


# -- top-level in-place function forms (paddle.sin_(x) etc.) ---------------
def _export_inplace_functions():
    import sys
    mod = sys.modules[__name__]
    names = [
        "abs", "acos", "add", "addmm", "asin", "atan", "bernoulli", "bitwise_and",
        "bitwise_left_shift", "bitwise_not", "bitwise_or",
        "bitwise_right_shift", "bitwise_xor", "cast", "cauchy", "ceil",
        "clip", "copysign", "cos", "cumprod", "cumsum", "digamma", "divide",
        "equal", "erf", "erfinv", "exp", "expm1", "exponential", "fill",
        "flatten", "floor", "floor_divide", "floor_mod", "frac", "gammainc",
        "gammaincc", "gammaln", "gcd", "geometric", "greater_equal",
        "greater_than", "hypot", "i0", "index_add", "index_fill",
        "index_put", "lcm", "ldexp", "lerp", "less_equal", "less_than",
        "lgamma", "log", "log10", "log1p", "log2", "log_normal", "logical_and",
        "logical_not", "logical_or", "logical_xor", "logit", "masked_fill",
        "masked_scatter", "mod", "multigammaln", "multiply", "nan_to_num",
        "neg", "normal", "not_equal", "polygamma", "pow", "put_along_axis",
        "reciprocal", "remainder", "renorm", "reshape", "round", "rsqrt",
        "scale", "scatter", "sigmoid", "sign", "sin", "sinc", "sinh",
        "sqrt", "square", "squeeze", "subtract", "t", "tan", "tanh",
        "transpose", "tril", "triu", "trunc", "uniform", "unsqueeze",
        "where", "zero",
    ]
    from .framework.tensor import Tensor as _T

    def make(n):
        method = n + "_"

        def fn(x, *args, **kwargs):
            return getattr(x, method)(*args, **kwargs)
        fn.__name__ = method
        fn.__doc__ = f"In-place form of paddle.{n} (mutates x)."
        return fn

    for n in names:
        if hasattr(_T, n + "_") and not hasattr(mod, n + "_"):
            setattr(mod, n + "_", make(n))


_export_inplace_functions()
