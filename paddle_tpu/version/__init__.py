"""paddle.version (reference: generated python/paddle/version/__init__.py)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
cuda_version = "False"
cudnn_version = "False"
tensorrt_version = None
xpu_version = "False"


def show():
    print(f"paddle_tpu {full_version} (tpu/xla backend)")


def cuda():
    return False


def cudnn():
    return False


def xpu():
    return False
