"""Sparse COO/CSR tensor types (reference: paddle/phi/core/sparse_coo_tensor.h,
sparse_csr_tensor.h)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor

__all__ = ["SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
           "sparse_csr_tensor"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


class SparseCooTensor:
    """indices [ndim, nnz] + values [nnz, ...]; static nnz."""

    def __init__(self, indices, values, shape, coalesced=False):
        self.indices_ = _arr(indices).astype(jnp.int64)
        self.values_ = _arr(values)
        self.shape = list(shape)
        self._coalesced = coalesced

    def indices(self):
        return Tensor(self.indices_)

    def values(self):
        # sparse-layer outputs carry their taped Tensor so a loss built
        # from .values() backprops into the layer parameters
        vt = getattr(self, "_values_t", None)
        return vt if vt is not None else Tensor(self.values_)

    @property
    def nnz(self):
        return self.indices_.shape[1]

    @property
    def dtype(self):
        from ..framework.dtype import dtype as _dt
        return _dt(str(self.values_.dtype))

    def to_dense(self):
        dense = jnp.zeros(tuple(self.shape), self.values_.dtype)
        idx = tuple(self.indices_[i] for i in range(self.indices_.shape[0]))
        return Tensor(dense.at[idx].add(self.values_))

    def to_sparse_csr(self):
        assert len(self.shape) == 2
        order = jnp.lexsort((self.indices_[1], self.indices_[0]))
        rows = self.indices_[0][order]
        cols = self.indices_[1][order]
        vals = self.values_[order]
        crows = jnp.searchsorted(rows, jnp.arange(self.shape[0] + 1))
        return SparseCsrTensor(crows, cols, vals, self.shape)

    def coalesce(self):
        nd = self.indices_.shape[0]
        flat = jnp.zeros_like(self.indices_[0])
        for i in range(nd):
            flat = flat * self.shape[i] + self.indices_[i]
        order = jnp.argsort(flat)
        sflat, svals = flat[order], self.values_[order]
        uniq, inv = jnp.unique(sflat, return_inverse=True,
                               size=self.nnz, fill_value=-1)
        summed = jnp.zeros((self.nnz,) + self.values_.shape[1:],
                           self.values_.dtype).at[inv].add(svals)
        new_idx = []
        rem = uniq
        for s in reversed(self.shape[:nd]):
            new_idx.append(rem % s)
            rem = rem // s
        idx = jnp.stack(list(reversed(new_idx)))
        keep = uniq >= 0
        return SparseCooTensor(jnp.where(keep[None], idx, 0),
                               jnp.where(
                                   keep.reshape((-1,) + (1,) * (summed.ndim - 1)),
                                   summed, 0),
                               self.shape, coalesced=True)

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz},\n"
                f"  indices={np.asarray(self.indices_)},\n"
                f"  values={np.asarray(self.values_)})")


class SparseCsrTensor:
    def __init__(self, crows, cols, values, shape):
        self.crows_ = _arr(crows).astype(jnp.int64)
        self.cols_ = _arr(cols).astype(jnp.int64)
        self.values_ = _arr(values)
        self.shape = list(shape)

    def crows(self):
        return Tensor(self.crows_)

    def cols(self):
        return Tensor(self.cols_)

    def values(self):
        return Tensor(self.values_)

    @property
    def nnz(self):
        return self.cols_.shape[0]

    def to_dense(self):
        rows = jnp.searchsorted(self.crows_,
                                jnp.arange(self.nnz), side="right") - 1
        dense = jnp.zeros(tuple(self.shape), self.values_.dtype)
        return Tensor(dense.at[rows, self.cols_].add(self.values_))

    def to_sparse_coo(self, sparse_dim=2):
        rows = jnp.searchsorted(self.crows_,
                                jnp.arange(self.nnz), side="right") - 1
        return SparseCooTensor(jnp.stack([rows, self.cols_]),
                               self.values_, self.shape)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    ind = _arr(indices)
    val = _arr(values)
    if shape is None:
        mx = np.asarray(jnp.max(ind, axis=1)) + 1
        shape = [int(v) for v in mx] + list(val.shape[1:])
    return SparseCooTensor(ind, val, shape)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    return SparseCsrTensor(_arr(crows), _arr(cols), _arr(values), shape)
