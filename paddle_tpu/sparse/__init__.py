"""paddle.sparse (reference: python/paddle/sparse + paddle/phi/kernels/sparse
— COO/CSR tensors and ops).

TPU reality: XLA has no sparse HLO; the idiomatic mapping keeps a COO/CSR
*format* layer (indices/values arrays, static nnz) whose compute lowers to
dense/segment-sum XLA ops — the same trade the reference's sparse GPU
kernels make per-block.  Good for the API surface + moderate sparsity.
"""
from .coo import (  # noqa: F401
    SparseCooTensor, SparseCsrTensor, sparse_coo_tensor, sparse_csr_tensor)
from . import nn  # noqa: F401
from .unary import (  # noqa: F401
    sin, tanh, relu, abs, sqrt, square, log1p, neg, expm1, cast, pow,
    asin, asinh, atan, atanh, sinh, tan, deg2rad, rad2deg, isnan, sum,
    transpose, reshape, slice, coalesce, is_same_shape, mask_as)
from .binary import (  # noqa: F401
    add, subtract, multiply, divide, matmul, masked_matmul, mv, addmm,
    pca_lowrank)
