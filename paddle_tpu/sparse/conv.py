"""Sparse 3D convolutions (point-cloud workloads).

Reference: paddle/phi/kernels/sparse/gpu/conv_kernel.cu +
python/paddle/sparse/nn/layer/conv.py (Conv3D, SubmConv3D) and the
gather-GEMM-scatter "rulebook" machinery (paddle/phi/kernels/sparse/
gpu/gather_gemm_scatter.h).

TPU formulation: the rulebook (per-kernel-offset lists of (input_site,
output_site) pairs) is built HOST-side from the concrete COO indices —
eager sparse tensors carry concrete coordinates, exactly like the
reference's rulebook build on device — and the arithmetic runs on
device as one gather + batched matmul + scatter-add per kernel offset
(K³ MXU matmuls of [pairs_k, Cin] x [Cin, Cout]; no dense voxel grid is
ever materialized).

SubmConv3D keeps the output site set equal to the input's (submanifold
semantics — the standard choice in point-cloud backbones); Conv3D
computes the dilated output site set (union of input sites shifted by
kernel offsets, with stride).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .coo import SparseCooTensor

__all__ = ["subm_conv3d", "conv3d", "SubmConv3D", "Conv3D",
           "BatchNorm", "MaxPool3D"]


def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(int(x) for x in v)


def _host_coords(x: SparseCooTensor):
    # [nnz, 4] rows of (batch, d, h, w)
    return np.asarray(x.indices_).T


def _rulebook(in_coords, out_coords, kernel, stride, padding, dilation):
    """Per-offset (in_idx, out_idx) pair lists.

    out = (in + pad - off*dil) / stride for each kernel offset; a pair
    exists when the shifted input site lands exactly on an output site.
    """
    kd, kh, kw = kernel
    sd, sh, sw = stride
    pd, ph, pw = padding
    dd, dh, dw = dilation
    out_lut = {tuple(c): i for i, c in enumerate(map(tuple, out_coords))}
    book = []
    for od in range(kd):
        for oh in range(kh):
            for ow in range(kw):
                pairs = []
                for i, (b, d, h, w) in enumerate(in_coords):
                    zd = d + pd - od * dd
                    zh = h + ph - oh * dh
                    zw = w + pw - ow * dw
                    if zd % sd or zh % sh or zw % sw:
                        continue
                    j = out_lut.get((b, zd // sd, zh // sh, zw // sw))
                    if j is not None:
                        pairs.append((i, j))
                book.append(np.asarray(pairs, np.int64).reshape(-1, 2))
    return book


def _apply_rulebook(x, book, weight, bias, out_coords, out_spatial):
    w = jnp.asarray(weight)          # [kd, kh, kw, Cin, Cout]
    cout = w.shape[-1]
    n_out = len(out_coords)
    out = jnp.zeros((n_out, cout), x.values_.dtype)
    wk = w.reshape(-1, w.shape[-2], cout)
    for k, pairs in enumerate(book):
        if len(pairs) == 0:
            continue
        gathered = x.values_[jnp.asarray(pairs[:, 0])]       # [p, Cin]
        contrib = gathered @ wk[k].astype(gathered.dtype)    # MXU matmul
        out = out.at[jnp.asarray(pairs[:, 1])].add(contrib)
    if bias is not None:
        out = out + jnp.asarray(bias).astype(out.dtype)
    shape = [x.shape[0], *out_spatial, cout]
    return SparseCooTensor(jnp.asarray(out_coords.T), out, shape,
                           coalesced=True)


def subm_conv3d(x: SparseCooTensor, weight, bias=None, stride=1,
                padding=0, dilation=1, key=None):
    """Submanifold sparse conv: output sites == input sites (reference
    SubmConv3d; stride must be 1 — same contract as the reference)."""
    stride = _triple(stride)
    if stride != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1 "
                         "(submanifold semantics); use conv3d")
    kernel = jnp.asarray(weight).shape[:3]
    coords = _host_coords(x)
    pad = tuple((k - 1) // 2 * d for k, d in
                zip(kernel, _triple(dilation)))
    if padding != 0 and _triple(padding) != pad:
        raise ValueError(f"subm_conv3d implies 'same' padding {pad}")
    book = _rulebook(coords, coords, kernel, (1, 1, 1), pad,
                     _triple(dilation))
    return _apply_rulebook(x, book, weight, bias, coords, x.shape[1:4])


def conv3d(x: SparseCooTensor, weight, bias=None, stride=1, padding=0,
           dilation=1, key=None):
    """Standard sparse conv: the output site set is every voxel any
    kernel tap reaches (reference Conv3d)."""
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    kernel = tuple(jnp.asarray(weight).shape[:3])
    coords = _host_coords(x)
    spatial = x.shape[1:4]
    out_spatial = tuple(
        (spatial[i] + 2 * padding[i]
         - dilation[i] * (kernel[i] - 1) - 1) // stride[i] + 1
        for i in range(3))

    # one pass: enumerate output sites AND the per-offset rulebook
    seen = {}
    book = [[] for _ in range(kernel[0] * kernel[1] * kernel[2])]
    for i, (b, d, h, w) in enumerate(coords):
        k = 0
        for od in range(kernel[0]):
            for oh in range(kernel[1]):
                for ow in range(kernel[2]):
                    zd = d + padding[0] - od * dilation[0]
                    zh = h + padding[1] - oh * dilation[1]
                    zw = w + padding[2] - ow * dilation[2]
                    if not (zd % stride[0] or zh % stride[1]
                            or zw % stride[2]):
                        zd //= stride[0]
                        zh //= stride[1]
                        zw //= stride[2]
                        if 0 <= zd < out_spatial[0] and \
                                0 <= zh < out_spatial[1] and \
                                0 <= zw < out_spatial[2]:
                            j = seen.setdefault((b, zd, zh, zw),
                                                len(seen))
                            book[k].append((i, j))
                    k += 1
    out_coords = np.asarray(sorted(seen, key=seen.get), np.int64)
    if out_coords.size == 0:
        out_coords = out_coords.reshape(0, 4)
    book = [np.asarray(p, np.int64).reshape(-1, 2) for p in book]
    return _apply_rulebook(x, book, weight, bias, out_coords, out_spatial)


class _ConvBase:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..framework.tensor import Tensor

        if groups != 1:
            raise NotImplementedError("sparse conv groups != 1")
        k = _triple(kernel_size)
        fan_in = in_channels * k[0] * k[1] * k[2]
        # repo initializer infra: keys come from the global generator so
        # paddle.seed reproduces init and stacked layers differ
        from ..nn.initializer import Uniform
        bound = 1.0 / np.sqrt(fan_in)
        init = Uniform(-bound, bound)
        self.weight = Tensor(
            init(k + (in_channels, out_channels), "float32"),
            stop_gradient=False)
        self.bias = None
        if bias_attr is not False:
            self.bias = Tensor(jnp.zeros((out_channels,)),
                               stop_gradient=False)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None
                                else [])

    def _wb(self):
        b = None if self.bias is None else self.bias._data
        return self.weight._data, b


class SubmConv3D(_ConvBase):
    """reference python/paddle/sparse/nn/layer/conv.py SubmConv3D."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # the constructor must not accept configs the math ignores
        if _triple(self._stride) != (1, 1, 1):
            raise ValueError("SubmConv3D requires stride 1 "
                             "(submanifold semantics); use Conv3D")

    def __call__(self, x):
        w, b = self._wb()
        return subm_conv3d(x, w, b, stride=1, padding=self._padding,
                           dilation=self._dilation)

    forward = __call__


class Conv3D(_ConvBase):
    """reference python/paddle/sparse/nn/layer/conv.py Conv3D."""

    def __call__(self, x):
        w, b = self._wb()
        return conv3d(x, w, b, stride=self._stride,
                      padding=self._padding, dilation=self._dilation)

    forward = __call__


class BatchNorm:
    """Sparse batch norm: normalizes over the nnz values per channel
    (reference python/paddle/sparse/nn/layer/norm.py BatchNorm)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        from ..framework.tensor import Tensor

        self.num_features = num_features
        self._momentum = momentum
        self._eps = epsilon
        # trainable affine (matches the dense BatchNorm layers)
        self.weight = Tensor(jnp.ones((num_features,)),
                             stop_gradient=False)
        self.bias = Tensor(jnp.zeros((num_features,)),
                           stop_gradient=False)
        self._mean = jnp.zeros((num_features,))
        self._var = jnp.ones((num_features,))
        self.training = True

    def parameters(self):
        return [self.weight, self.bias]

    def __call__(self, x: SparseCooTensor):
        v = x.values_.astype(jnp.float32)
        if self.training:
            m = v.mean(axis=0)
            var = jnp.maximum(v.var(axis=0), 0.0)
            self._mean = self._momentum * self._mean + \
                (1 - self._momentum) * m
            self._var = self._momentum * self._var + \
                (1 - self._momentum) * var
        else:
            m, var = self._mean, self._var
        out = (v - m) * jnp.reciprocal(jnp.sqrt(var + self._eps))
        out = out * self.weight._data + self.bias._data
        return SparseCooTensor(x.indices_, out.astype(x.values_.dtype),
                               x.shape, coalesced=x._coalesced)

    def eval(self):
        self.training = False
        return self

    def train(self):
        self.training = True
        return self


class MaxPool3D:
    """Sparse max pool over active sites (reference
    python/paddle/sparse/nn/layer/pooling.py MaxPool3D)."""

    def __init__(self, kernel_size, stride=None, padding=0):
        self._kernel = _triple(kernel_size)
        self._stride = _triple(stride if stride is not None
                               else kernel_size)
        self._padding = _triple(padding)

    def __call__(self, x: SparseCooTensor):
        kernel, stride, padding = self._kernel, self._stride, self._padding
        coords = _host_coords(x)
        spatial = x.shape[1:4]
        out_spatial = tuple(
            (spatial[i] + 2 * padding[i] - kernel[i]) // stride[i] + 1
            for i in range(3))

        def windows(pos, axis):
            """All output positions whose window covers `pos` on `axis`
            (overlapping pools: kernel > stride means several)."""
            p = pos + padding[axis]
            lo = max(0, -(-(p - kernel[axis] + 1) // stride[axis]))
            hi = min(out_spatial[axis] - 1, p // stride[axis])
            return range(lo, hi + 1)

        seen = {}
        pairs = []
        for i, (b, d, h, w) in enumerate(coords):
            for zd in windows(d, 0):
                for zh in windows(h, 1):
                    for zw in windows(w, 2):
                        j = seen.setdefault((b, zd, zh, zw), len(seen))
                        pairs.append((i, j))
        out_coords = np.asarray(sorted(seen, key=seen.get), np.int64)
        if out_coords.size == 0:
            out_coords = out_coords.reshape(0, 4)
        pairs = np.asarray(pairs, np.int64).reshape(-1, 2)
        c = x.values_.shape[-1]
        out = jnp.full((len(out_coords), c), -jnp.inf, x.values_.dtype)
        if len(pairs):
            out = out.at[jnp.asarray(pairs[:, 1])].max(
                x.values_[jnp.asarray(pairs[:, 0])])
        shape = [x.shape[0], *out_spatial, c]
        return SparseCooTensor(jnp.asarray(out_coords.T), out, shape,
                               coalesced=True)

    forward = __call__
