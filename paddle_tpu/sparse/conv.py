"""Sparse 3D convolutions (point-cloud workloads).

Reference: paddle/phi/kernels/sparse/gpu/conv_kernel.cu +
python/paddle/sparse/nn/layer/conv.py (Conv3D, SubmConv3D) and the
gather-GEMM-scatter "rulebook" machinery (paddle/phi/kernels/sparse/
gpu/gather_gemm_scatter.h).

TPU formulation: the rulebook (per-kernel-offset lists of (input_site,
output_site) pairs) is built HOST-side from the concrete COO indices —
eager sparse tensors carry concrete coordinates, exactly like the
reference's rulebook build on device — and the arithmetic runs on
device as one gather + matmul + scatter-add per kernel offset (K³ MXU
matmuls of [pairs_k, Cin] x [Cin, Cout]; no dense voxel grid is ever
materialized).  The device math is pure in (values, weight, bias) with
the index arrays as constants, so layer calls record ONE tape GradNode
via ``jax.vjp`` and the whole conv→bn→pool pipeline trains.

SubmConv3D keeps the output site set equal to the input's (submanifold
semantics — the standard choice in point-cloud backbones); Conv3D
computes the dilated output site set (union of input sites shifted by
kernel offsets, with stride).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coo import SparseCooTensor

__all__ = ["subm_conv3d", "conv3d", "SubmConv3D", "Conv3D",
           "BatchNorm", "MaxPool3D"]


# ------------------------------------------------------------ tape glue
def _taped(fn, tensors, *arrays):
    """Run ``fn(*tensor_datas, *arrays)`` with autograd-tape recording:
    grads flow back to ``tensors`` through ``jax.vjp`` (the rulebook
    index arrays ride along as non-differentiable constants).  Returns a
    framework Tensor."""
    from ..autograd import tape
    from ..framework.tensor import Tensor

    datas = [t._data for t in tensors]
    if not (tape.is_grad_enabled()
            and any(not t.stop_gradient for t in tensors)):
        return Tensor(fn(*datas, *arrays))
    out, vjp = jax.vjp(lambda *ds: fn(*ds, *arrays), *datas)
    out_t = Tensor(out, stop_gradient=False)

    def vjp_fn(cots):
        return tuple(vjp(cots[0]))

    out_t._grad_node = tape.GradNode(
        "sparse_op", vjp_fn, tensors,
        [jax.ShapeDtypeStruct(out.shape, out.dtype)])
    out_t._out_index = 0
    return out_t


def _as_value_tensor(x: SparseCooTensor):
    """The taped value carrier for a sparse tensor (leaf inputs get a
    stop-gradient wrapper; outputs of sparse layers carry their taped
    Tensor in ``_values_t`` so the chain stays connected)."""
    from ..framework.tensor import Tensor

    vt = getattr(x, "_values_t", None)
    if vt is not None:
        return vt
    return Tensor(x.values_, stop_gradient=True)


def _with_values(coords_t, values_t, shape, coalesced=True):
    out = SparseCooTensor(coords_t, values_t._data, shape,
                          coalesced=coalesced)
    out._values_t = values_t
    return out


# ------------------------------------------------------------- planning
def _triple(v):
    return (v, v, v) if isinstance(v, int) else tuple(int(x) for x in v)


def _ensure_coalesced(x: SparseCooTensor):
    # duplicate coordinates would collapse onto one rulebook output row;
    # sum duplicates first (reference rulebook assumes unique sites).
    # Concrete host-side merge — NOT SparseCooTensor.coalesce(), whose
    # jit-safe static-nnz padding would inject a phantom site at the
    # origin.
    if getattr(x, "_coalesced", False):
        return x
    coords = np.asarray(x.indices_).T
    uniq, inv = np.unique(coords, axis=0, return_inverse=True)
    if len(uniq) == len(coords):
        x._coalesced = True       # cache: the scan proved no duplicates
        return x
    vals = jnp.zeros((len(uniq),) + x.values_.shape[1:],
                     x.values_.dtype).at[jnp.asarray(inv)].add(x.values_)
    return SparseCooTensor(jnp.asarray(uniq.T), vals, x.shape,
                           coalesced=True)


def _host_coords(x: SparseCooTensor):
    # [nnz, 4] rows of (batch, d, h, w)
    return np.asarray(x.indices_).T


def _coords_array(seen):
    """Insertion-ordered site dict -> [n, 4] int64 array."""
    out = np.asarray(list(seen), np.int64)
    return out.reshape(-1, 4) if out.size else out.reshape(0, 4)


def _plan_subm(coords, kernel, dilation):
    """Rulebook with output sites == input sites ('same' padding)."""
    kd, kh, kw = kernel
    dd, dh, dw = dilation
    pd, ph, pw = ((kd - 1) // 2 * dd, (kh - 1) // 2 * dh,
                  (kw - 1) // 2 * dw)
    lut = {tuple(c): i for i, c in enumerate(map(tuple, coords))}
    book = []
    for od in range(kd):
        for oh in range(kh):
            for ow in range(kw):
                pairs = []
                for i, (b, d, h, w) in enumerate(coords):
                    j = lut.get((b, d + pd - od * dd, h + ph - oh * dh,
                                 w + pw - ow * dw))
                    if j is not None:
                        pairs.append((i, j))
                book.append(np.asarray(pairs, np.int64).reshape(-1, 2))
    return book


def _conv_plan(x, kernel, stride, padding, dilation):
    """Shared Conv3D planning: (book, out_coords, out_spatial) from a
    coalesced sparse input — used by both the functional and layer
    paths so the output-shape arithmetic lives once."""
    stride = _triple(stride)
    padding = _triple(padding)
    dilation = _triple(dilation)
    coords = _host_coords(x)
    spatial = x.shape[1:4]
    out_spatial = tuple(
        (spatial[i] + 2 * padding[i]
         - dilation[i] * (kernel[i] - 1) - 1) // stride[i] + 1
        for i in range(3))
    book, out_coords = _plan_conv(coords, kernel, stride, padding,
                                  dilation, out_spatial)
    return book, out_coords, out_spatial


def _plan_conv(coords, kernel, stride, padding, dilation, out_spatial):
    """One pass: output sites AND the per-offset rulebook."""
    seen = {}
    book = [[] for _ in range(kernel[0] * kernel[1] * kernel[2])]
    for i, (b, d, h, w) in enumerate(coords):
        k = 0
        for od in range(kernel[0]):
            for oh in range(kernel[1]):
                for ow in range(kernel[2]):
                    zd = d + padding[0] - od * dilation[0]
                    zh = h + padding[1] - oh * dilation[1]
                    zw = w + padding[2] - ow * dilation[2]
                    if not (zd % stride[0] or zh % stride[1]
                            or zw % stride[2]):
                        zd //= stride[0]
                        zh //= stride[1]
                        zw //= stride[2]
                        if 0 <= zd < out_spatial[0] and \
                                0 <= zh < out_spatial[1] and \
                                0 <= zw < out_spatial[2]:
                            j = seen.setdefault((b, zd, zh, zw),
                                                len(seen))
                            book[k].append((i, j))
                    k += 1
    book = [np.asarray(p, np.int64).reshape(-1, 2) for p in book]
    return book, _coords_array(seen)


def _conv_fn(book, n_out):
    """Pure device math: (values [nnz, Cin], w [kd,kh,kw,Cin,Cout],
    bias?) -> [n_out, Cout].  Differentiable in all three."""
    def fn(values, w, b=None):
        cout = w.shape[-1]
        wk = w.reshape(-1, w.shape[-2], cout)
        out = jnp.zeros((n_out, cout), values.dtype)
        for k, pairs in enumerate(book):
            if len(pairs) == 0:
                continue
            gathered = values[jnp.asarray(pairs[:, 0])]
            contrib = gathered @ wk[k].astype(gathered.dtype)
            out = out.at[jnp.asarray(pairs[:, 1])].add(contrib)
        if b is not None:
            out = out + b.astype(out.dtype)
        return out
    return fn


# ----------------------------------------------------------- functional
def subm_conv3d(x: SparseCooTensor, weight, bias=None, stride=1,
                padding=0, dilation=1):
    """Submanifold sparse conv: output sites == input sites (reference
    SubmConv3d; stride must be 1 — same contract as the reference)."""
    if _triple(stride) != (1, 1, 1):
        raise ValueError("subm_conv3d requires stride 1 "
                         "(submanifold semantics); use conv3d")
    x = _ensure_coalesced(x)
    kernel = tuple(np.shape(weight)[:3])
    dilation = _triple(dilation)
    pad = tuple((k - 1) // 2 * d for k, d in zip(kernel, dilation))
    if _triple(padding) not in ((0, 0, 0), pad):
        raise ValueError(f"subm_conv3d implies 'same' padding {pad}")
    coords = _host_coords(x)
    book = _plan_subm(coords, kernel, dilation)
    fn = _conv_fn(book, len(coords))
    out = fn(jnp.asarray(x.values_), jnp.asarray(weight),
             None if bias is None else jnp.asarray(bias))
    shape = [x.shape[0], *x.shape[1:4], int(np.shape(weight)[-1])]
    return SparseCooTensor(jnp.asarray(coords.T), out, shape,
                           coalesced=True)


def conv3d(x: SparseCooTensor, weight, bias=None, stride=1, padding=0,
           dilation=1):
    """Standard sparse conv: the output site set is every voxel any
    kernel tap reaches (reference Conv3d)."""
    x = _ensure_coalesced(x)
    kernel = tuple(np.shape(weight)[:3])
    book, out_coords, out_spatial = _conv_plan(x, kernel, stride,
                                               padding, dilation)
    fn = _conv_fn(book, len(out_coords))
    out = fn(jnp.asarray(x.values_), jnp.asarray(weight),
             None if bias is None else jnp.asarray(bias))
    shape = [x.shape[0], *out_spatial, int(np.shape(weight)[-1])]
    return SparseCooTensor(jnp.asarray(out_coords.T), out, shape,
                           coalesced=True)


# --------------------------------------------------------------- layers
class _ConvBase:
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        from ..framework.tensor import Tensor

        if groups != 1:
            raise NotImplementedError("sparse conv groups != 1")
        if data_format != "NDHWC":
            raise ValueError("sparse conv supports NDHWC only "
                             "(reference contract)")
        if padding_mode != "zeros":
            raise NotImplementedError("sparse conv padding_mode != zeros")
        k = _triple(kernel_size)
        fan_in = in_channels * k[0] * k[1] * k[2]
        # repo initializer infra: keys come from the global generator so
        # paddle.seed reproduces init and stacked layers differ
        from ..nn.initializer import Uniform
        bound = 1.0 / np.sqrt(fan_in)
        init = Uniform(-bound, bound)
        self.weight = Tensor(
            init(k + (in_channels, out_channels), "float32"),
            stop_gradient=False)
        self.bias = None
        if bias_attr is not False:
            self.bias = Tensor(jnp.zeros((out_channels,)),
                               stop_gradient=False)
        self._kernel = k
        self._stride = stride
        self._padding = padding
        self._dilation = _triple(dilation)

    def parameters(self):
        return [self.weight] + ([self.bias] if self.bias is not None
                                else [])

    def _run(self, x, book, out_coords, out_spatial):
        vin = _as_value_tensor(x)
        tensors = [vin, self.weight]
        fn = _conv_fn(book, len(out_coords))
        if self.bias is not None:
            tensors.append(self.bias)
            vout = _taped(lambda v, w, b: fn(v, w, b), tensors)
        else:
            vout = _taped(lambda v, w: fn(v, w), tensors)
        cout = self.weight._data.shape[-1]
        shape = [x.shape[0], *out_spatial, int(cout)]
        return _with_values(jnp.asarray(out_coords.T), vout, shape)


class SubmConv3D(_ConvBase):
    """reference python/paddle/sparse/nn/layer/conv.py SubmConv3D."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if _triple(self._stride) != (1, 1, 1):
            raise ValueError("SubmConv3D requires stride 1 "
                             "(submanifold semantics); use Conv3D")
        same = tuple((k - 1) // 2 * d for k, d in
                     zip(self._kernel, self._dilation))
        if _triple(self._padding) not in ((0, 0, 0), same):
            raise ValueError(
                f"SubmConv3D implies 'same' padding {same}; "
                f"got {self._padding}")

    def __call__(self, x):
        x = _ensure_coalesced(x)
        coords = _host_coords(x)
        book = _plan_subm(coords, self._kernel, self._dilation)
        return self._run(x, book, coords, x.shape[1:4])

    forward = __call__


class Conv3D(_ConvBase):
    """reference python/paddle/sparse/nn/layer/conv.py Conv3D."""

    def __call__(self, x):
        x = _ensure_coalesced(x)
        book, out_coords, out_spatial = _conv_plan(
            x, self._kernel, self._stride, self._padding, self._dilation)
        return self._run(x, book, out_coords, out_spatial)

    forward = __call__


class BatchNorm:
    """Sparse batch norm: normalizes over the nnz values per channel
    (reference python/paddle/sparse/nn/layer/norm.py BatchNorm).
    Trainable affine; grads flow through the batch statistics."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5):
        from ..framework.tensor import Tensor

        self.num_features = num_features
        self._momentum = momentum
        self._eps = epsilon
        self.weight = Tensor(jnp.ones((num_features,)),
                             stop_gradient=False)
        self.bias = Tensor(jnp.zeros((num_features,)),
                           stop_gradient=False)
        self._mean = jnp.zeros((num_features,))
        self._var = jnp.ones((num_features,))
        self.training = True

    def parameters(self):
        return [self.weight, self.bias]

    def __call__(self, x: SparseCooTensor):
        vin = _as_value_tensor(x)
        if x.nnz == 0:
            # no values: stats are undefined; pass through untouched
            # (and never poison the running estimates with NaN)
            return x
        training = self.training
        eps = self._eps
        if training:
            mean, var = None, None
        else:
            mean, var = self._mean, self._var

        def fn(v, w, b):
            vf = v.astype(jnp.float32)
            if training:
                m = vf.mean(axis=0)
                s2 = jnp.maximum(vf.var(axis=0), 0.0)
            else:
                m, s2 = mean, var
            out = (vf - m) * jnp.reciprocal(jnp.sqrt(s2 + eps))
            return (out * w + b).astype(v.dtype)

        vout = _taped(fn, [vin, self.weight, self.bias])
        if training:
            # running-stat update stays on device (no host round-trip);
            # the taped fn recomputes the same stats so their GRADIENT
            # contribution flows — passing precomputed stats in would
            # silently drop the dmean/dvar terms of the BN backward
            vf = vin._data.astype(jnp.float32)
            self._mean = self._momentum * self._mean + \
                (1 - self._momentum) * vf.mean(axis=0)
            self._var = self._momentum * self._var + \
                (1 - self._momentum) * jnp.maximum(vf.var(axis=0), 0.0)
        return _with_values(x.indices_, vout, x.shape,
                            coalesced=getattr(x, "_coalesced", False))

    def eval(self):
        self.training = False
        return self

    def train(self):
        self.training = True
        return self


class MaxPool3D:
    """Sparse max pool over active sites (reference
    python/paddle/sparse/nn/layer/pooling.py MaxPool3D)."""

    def __init__(self, kernel_size, stride=None, padding=0):
        self._kernel = _triple(kernel_size)
        self._stride = _triple(stride if stride is not None
                               else kernel_size)
        self._padding = _triple(padding)

    def __call__(self, x: SparseCooTensor):
        x = _ensure_coalesced(x)
        kernel, stride, padding = self._kernel, self._stride, self._padding
        coords = _host_coords(x)
        spatial = x.shape[1:4]
        out_spatial = tuple(
            (spatial[i] + 2 * padding[i] - kernel[i]) // stride[i] + 1
            for i in range(3))

        def windows(pos, axis):
            """All output positions whose window covers `pos` on `axis`
            (overlapping pools: kernel > stride means several)."""
            p = pos + padding[axis]
            lo = max(0, -(-(p - kernel[axis] + 1) // stride[axis]))
            hi = min(out_spatial[axis] - 1, p // stride[axis])
            return range(lo, hi + 1)

        seen = {}
        pairs = []
        for i, (b, d, h, w) in enumerate(coords):
            for zd in windows(d, 0):
                for zh in windows(h, 1):
                    for zw in windows(w, 2):
                        j = seen.setdefault((b, zd, zh, zw), len(seen))
                        pairs.append((i, j))
        out_coords = _coords_array(seen)
        pairs = np.asarray(pairs, np.int64).reshape(-1, 2)
        n_out = len(out_coords)

        def fn(v):
            c = v.shape[-1]
            out = jnp.full((n_out, c), -jnp.inf, v.dtype)
            if len(pairs):
                out = out.at[jnp.asarray(pairs[:, 1])].max(
                    v[jnp.asarray(pairs[:, 0])])
            return out

        vout = _taped(fn, [_as_value_tensor(x)])
        c = x.values_.shape[-1]
        shape = [x.shape[0], *out_spatial, int(c)]
        return _with_values(jnp.asarray(out_coords.T), vout, shape)

    forward = __call__
