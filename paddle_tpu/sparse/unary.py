"""Sparse unary ops — apply to values, keep structure (reference:
paddle/phi/kernels/sparse/unary_kernel.h)."""
from __future__ import annotations

import jax.numpy as jnp

from .coo import SparseCooTensor, SparseCsrTensor

__all__ = ["sin", "tanh", "relu", "abs", "sqrt", "square", "log1p", "neg",
           "expm1", "cast", "pow"]


def _map_values(x, fn):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices_, fn(x.values_), x.shape,
                               x._coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows_, x.cols_, fn(x.values_), x.shape)
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def sin(x):
    return _map_values(x, jnp.sin)


def tanh(x):
    return _map_values(x, jnp.tanh)


def relu(x):
    return _map_values(x, lambda v: jnp.maximum(v, 0))


def abs(x):
    return _map_values(x, jnp.abs)


def sqrt(x):
    return _map_values(x, jnp.sqrt)


def square(x):
    return _map_values(x, jnp.square)


def log1p(x):
    return _map_values(x, jnp.log1p)


def neg(x):
    return _map_values(x, jnp.negative)


def expm1(x):
    return _map_values(x, jnp.expm1)


def pow(x, factor):
    return _map_values(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None):
    from ..framework.dtype import dtype as _dt
    out = x
    if value_dtype is not None:
        np_dt = _dt(value_dtype).np_dtype
        out = _map_values(out, lambda v: v.astype(np_dt))
    if index_dtype is not None and isinstance(out, SparseCooTensor):
        np_it = _dt(index_dtype).np_dtype
        out = SparseCooTensor(out.indices_.astype(np_it), out.values_,
                              out.shape, out._coalesced)
    return out


def asin(x):
    return _map_values(x, jnp.arcsin)


def asinh(x):
    return _map_values(x, jnp.arcsinh)


def atan(x):
    return _map_values(x, jnp.arctan)


def atanh(x):
    return _map_values(x, jnp.arctanh)


def sinh(x):
    return _map_values(x, jnp.sinh)


def tan(x):
    return _map_values(x, jnp.tan)


def deg2rad(x):
    return _map_values(x, jnp.deg2rad)


def rad2deg(x):
    return _map_values(x, jnp.rad2deg)


def isnan(x):
    return _map_values(x, jnp.isnan)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    """Sparse reduce-sum (reference sparse/unary.py sum): dense result
    unless reducing nothing."""
    from ..framework.tensor import Tensor
    dense = x.to_dense()._data
    out = jnp.sum(dense, axis=axis, keepdims=keepdim)
    if dtype is not None:
        from ..framework.dtype import to_np_dtype
        out = out.astype(to_np_dtype(dtype))
    return Tensor(out)


def transpose(x, perm, name=None):
    from .coo import SparseCooTensor
    if isinstance(x, SparseCooTensor):
        idx = jnp.stack([x.indices_[p] for p in perm])
        shape = [x.shape[p] for p in perm]
        return SparseCooTensor(idx, x.values_, shape)
    # CSR: via COO
    return transpose(x.to_sparse_coo(), perm).to_sparse_csr()


def reshape(x, shape, name=None):
    from .coo import SparseCooTensor
    import numpy as _np
    old_shape = x.shape
    new_shape = list(shape)
    numel = int(_np.prod(old_shape))
    if -1 in new_shape:
        known = int(_np.prod([t for t in new_shape if t != -1]))
        new_shape[new_shape.index(-1)] = numel // max(known, 1)
    if isinstance(x, SparseCooTensor):
        nd = x.indices_.shape[0]
        flat = jnp.zeros_like(x.indices_[0])
        for i in range(nd):
            flat = flat * old_shape[i] + x.indices_[i]
        idx = []
        rem = flat
        for s in new_shape[::-1]:
            idx.append(rem % s)
            rem = rem // s
        return SparseCooTensor(jnp.stack(idx[::-1]), x.values_, new_shape)
    return reshape(x.to_sparse_coo(), shape).to_sparse_csr()


def slice(x, axes, starts, ends, name=None):
    """Sparse slice (reference sparse/unary.py slice): filter coordinates
    inside the window."""
    from .coo import SparseCooTensor
    from ..framework.tensor import Tensor as _T
    coo = x if isinstance(x, SparseCooTensor) else x.to_sparse_coo()
    # static-shape unfriendly (nnz changes): computed on host
    import numpy as _np
    idx = _np.asarray(coo.indices_)
    vals = _np.asarray(coo.values_)
    keep = _np.ones(idx.shape[1], bool)
    new_shape = list(coo.shape)
    for ax, st, en in zip(axes, starts, ends):
        st = st + coo.shape[ax] if st < 0 else st
        en = min(en + coo.shape[ax] if en < 0 else en, coo.shape[ax])
        keep &= (idx[ax] >= st) & (idx[ax] < en)
        new_shape[ax] = en - st
    idx = idx[:, keep].copy()
    for ax, st, _ in zip(axes, starts, ends):
        st = st + coo.shape[ax] if st < 0 else st
        idx[ax] -= st
    out = SparseCooTensor(jnp.asarray(idx), jnp.asarray(vals[keep]),
                          new_shape)
    return out if isinstance(x, SparseCooTensor) else out.to_sparse_csr()


def coalesce(x, name=None):
    return x.coalesce()


def is_same_shape(x, y):
    return list(x.shape) == list(y.shape)


def mask_as(x, mask, name=None):
    """Dense x masked by the sparsity pattern of `mask` (reference
    sparse/unary.py mask_as)."""
    from .coo import SparseCooTensor, SparseCsrTensor
    dense = x._data if hasattr(x, "_data") else jnp.asarray(x)
    if isinstance(mask, SparseCooTensor):
        idx = tuple(mask.indices_[i] for i in range(mask.indices_.shape[0]))
        return SparseCooTensor(mask.indices_, dense[idx], mask.shape)
    coo = mask.to_sparse_coo()
    idx = tuple(coo.indices_[i] for i in range(coo.indices_.shape[0]))
    return SparseCooTensor(coo.indices_, dense[idx],
                           coo.shape).to_sparse_csr()
