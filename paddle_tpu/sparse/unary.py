"""Sparse unary ops — apply to values, keep structure (reference:
paddle/phi/kernels/sparse/unary_kernel.h)."""
from __future__ import annotations

import jax.numpy as jnp

from .coo import SparseCooTensor, SparseCsrTensor

__all__ = ["sin", "tanh", "relu", "abs", "sqrt", "square", "log1p", "neg",
           "expm1", "cast", "pow"]


def _map_values(x, fn):
    if isinstance(x, SparseCooTensor):
        return SparseCooTensor(x.indices_, fn(x.values_), x.shape,
                               x._coalesced)
    if isinstance(x, SparseCsrTensor):
        return SparseCsrTensor(x.crows_, x.cols_, fn(x.values_), x.shape)
    raise TypeError(f"expected sparse tensor, got {type(x)}")


def sin(x):
    return _map_values(x, jnp.sin)


def tanh(x):
    return _map_values(x, jnp.tanh)


def relu(x):
    return _map_values(x, lambda v: jnp.maximum(v, 0))


def abs(x):
    return _map_values(x, jnp.abs)


def sqrt(x):
    return _map_values(x, jnp.sqrt)


def square(x):
    return _map_values(x, jnp.square)


def log1p(x):
    return _map_values(x, jnp.log1p)


def neg(x):
    return _map_values(x, jnp.negative)


def expm1(x):
    return _map_values(x, jnp.expm1)


def pow(x, factor):
    return _map_values(x, lambda v: jnp.power(v, factor))


def cast(x, index_dtype=None, value_dtype=None):
    from ..framework.dtype import dtype as _dt
    out = x
    if value_dtype is not None:
        np_dt = _dt(value_dtype).np_dtype
        out = _map_values(out, lambda v: v.astype(np_dt))
    if index_dtype is not None and isinstance(out, SparseCooTensor):
        np_it = _dt(index_dtype).np_dtype
        out = SparseCooTensor(out.indices_.astype(np_it), out.values_,
                              out.shape, out._coalesced)
    return out
