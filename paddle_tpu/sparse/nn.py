"""sparse.nn (reference: python/paddle/sparse/nn — ReLU/Softmax plus the
point-cloud stack: Conv3D/SubmConv3D/BatchNorm/MaxPool3D)."""
from __future__ import annotations

import jax.numpy as jnp

from . import unary
from .coo import SparseCooTensor, SparseCsrTensor
from .conv import (Conv3D, SubmConv3D, BatchNorm, MaxPool3D,  # noqa: F401
                   conv3d, subm_conv3d)

__all__ = ["ReLU", "Softmax", "Conv3D", "SubmConv3D", "BatchNorm",
           "MaxPool3D"]


class ReLU:
    def __call__(self, x):
        return unary.relu(x)


class Softmax:
    """Row-wise softmax over a 2-D sparse matrix's nnz (reference
    sparse/nn/functional/activation.py softmax)."""

    def __init__(self, axis=-1):
        assert axis == -1

    def __call__(self, x):
        csr = x.to_sparse_csr() if isinstance(x, SparseCooTensor) else x
        rows = jnp.searchsorted(csr.crows_,
                                jnp.arange(csr.nnz), side="right") - 1
        v = csr.values_
        rmax = jnp.full((csr.shape[0],), -jnp.inf, v.dtype).at[rows].max(v)
        e = jnp.exp(v - rmax[rows])
        rsum = jnp.zeros((csr.shape[0],), v.dtype).at[rows].add(e)
        out = SparseCsrTensor(csr.crows_, csr.cols_, e / rsum[rows],
                              csr.shape)
        if isinstance(x, SparseCooTensor):
            return out.to_sparse_coo()
        return out
