"""Sparse binary ops (reference: paddle/phi/kernels/sparse/
elementwise_kernel.h, matmul_kernel.h)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.tensor import Tensor
from .coo import SparseCooTensor, SparseCsrTensor

__all__ = ["add", "subtract", "multiply", "divide", "matmul",
           "masked_matmul"]


def _ew(x, y, fn):
    """Same-structure elementwise via dense roundtrip (API parity; the
    reference GPU kernels do a merge — on TPU dense is the fast path)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)) and \
            isinstance(y, (SparseCooTensor, SparseCsrTensor)):
        d = fn(x.to_dense()._data, y.to_dense()._data)
        return _dense_to_coo(d)
    raise TypeError("sparse binary ops need two sparse tensors")


def _dense_to_coo(d):
    idx = jnp.stack(jnp.nonzero(d, size=int((d != 0).sum())))
    vals = d[tuple(idx[i] for i in range(idx.shape[0]))]
    return SparseCooTensor(idx, vals, list(d.shape))


def add(x, y):
    return _ew(x, y, jnp.add)


def subtract(x, y):
    return _ew(x, y, jnp.subtract)


def multiply(x, y):
    return _ew(x, y, jnp.multiply)


def divide(x, y):
    return _ew(x, y, jnp.divide)


def matmul(x, y):
    """sparse @ dense -> dense (reference sparse matmul_kernel)."""
    if isinstance(x, SparseCsrTensor):
        x = x.to_sparse_coo()
    if isinstance(x, SparseCooTensor):
        ydat = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        rows, cols = x.indices_[0], x.indices_[1]
        # segment-sum over rows: TPU-friendly scatter-add
        contrib = x.values_[:, None] * ydat[cols]
        out = jnp.zeros((x.shape[0], ydat.shape[1]), contrib.dtype)
        return Tensor(out.at[rows].add(contrib))
    raise TypeError(f"expected sparse lhs, got {type(x)}")


def masked_matmul(x, y, mask):
    """dense @ dense evaluated only at mask's nnz (reference
    masked_matmul_kernel — SDDMM)."""
    xd = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    yd = y._data if isinstance(y, Tensor) else jnp.asarray(y)
    if isinstance(mask, SparseCsrTensor):
        coo = mask.to_sparse_coo()
    else:
        coo = mask
    rows, cols = coo.indices_[0], coo.indices_[1]
    vals = jnp.sum(xd[rows] * yd[:, cols].T, axis=-1)
    if isinstance(mask, SparseCsrTensor):
        return SparseCsrTensor(mask.crows_, mask.cols_, vals, mask.shape)
    return SparseCooTensor(coo.indices_, vals, coo.shape)


def mv(x, vec, name=None):
    """Sparse matrix x dense vector (reference sparse/binary.py mv)."""
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    v = vec._data if hasattr(vec, "_data") else jnp.asarray(vec)
    return Tensor(jnp.matmul(x.to_dense()._data, v))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference
    sparse/binary.py addmm)."""
    from ..framework.tensor import Tensor
    import jax.numpy as jnp
    inp = input._data if hasattr(input, "_data") else jnp.asarray(input)
    yv = y._data if hasattr(y, "_data") else jnp.asarray(y)
    return Tensor(beta * inp + alpha * jnp.matmul(x.to_dense()._data, yv))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over a (sparse) matrix (reference
    sparse/binary.py pca_lowrank)."""
    from ..framework.tensor import Tensor
    from ..ops.linalg import svd_lowrank
    import jax.numpy as jnp
    dense = x.to_dense() if hasattr(x, "to_dense") else x
    d = dense._data if hasattr(dense, "_data") else jnp.asarray(dense)
    if center:
        d = d - jnp.mean(d, axis=0, keepdims=True)
    if q is None:
        q = min(6, *d.shape)
    return svd_lowrank(Tensor(d), q=q, niter=niter)
