"""Probability distributions (reference: python/paddle/distribution/*.py —
Distribution base with sample/log_prob/entropy/kl_divergence).

Sampling threads the framework PRNG (framework/random.py) so dygraph
sampling is reproducible under paddle.seed, and traceable under jit.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.tensor import Tensor
from ..framework import random as _random
from ..ops.registry import op

__all__ = ["Distribution", "Normal", "Uniform", "Categorical", "Bernoulli",
           "Exponential", "Beta", "Dirichlet", "Gamma", "Laplace",
           "LogNormal", "Multinomial", "Poisson", "Geometric", "Cauchy",
           "Gumbel", "StudentT", "kl_divergence"]


def _arr(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x, jnp.float32) if not isinstance(x, jnp.ndarray) \
        else x


def _t(x):
    return Tensor(x, stop_gradient=True)


def _shape(sample_shape, *params):
    base = jnp.broadcast_shapes(*[np.shape(p) for p in params]) \
        if params else ()
    return tuple(sample_shape) + tuple(base)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        return self.sample(shape)

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        import paddle_tpu as P
        return P.exp(self.log_prob(value))

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        # the registry-aware dispatcher (falls back to the pairs below)
        from .distributions_extra import kl_divergence as _kl
        return _kl(self, other)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(np.shape(self.loc))

    @property
    def mean(self):
        return _t(jnp.broadcast_to(self.loc, jnp.broadcast_shapes(
            np.shape(self.loc), np.shape(self.scale))))

    @property
    def variance(self):
        return _t(jnp.broadcast_to(jnp.square(self.scale),
                                   jnp.broadcast_shapes(
                                       np.shape(self.loc),
                                       np.shape(self.scale))))

    def sample(self, shape=()):
        sh = _shape(shape, self.loc, self.scale)
        eps = jax.random.normal(_random.split_key(), sh)
        return _t(self.loc + eps * self.scale)

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        var = jnp.square(self.scale)
        return _t(-jnp.square(v - self.loc) / (2 * var)
                  - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        return _t(0.5 + 0.5 * math.log(2 * math.pi)
                  + jnp.log(self.scale)
                  + jnp.zeros(np.shape(self.loc)))


class LogNormal(Normal):
    def sample(self, shape=()):
        return _t(jnp.exp(super().sample(shape)._data))

    def log_prob(self, value):
        v = _arr(value)
        logv = jnp.log(v)
        base = super().log_prob(_t(logv))._data
        return _t(base - logv)

    @property
    def mean(self):
        return _t(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return _t((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def entropy(self):
        return _t(0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
                  + self.loc + jnp.zeros(np.shape(self.scale)))


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)
        super().__init__(np.shape(self.low))

    def sample(self, shape=()):
        sh = _shape(shape, self.low, self.high)
        u = jax.random.uniform(_random.split_key(), sh)
        return _t(self.low + u * (self.high - self.low))

    rsample = sample

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return _t(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return _t(jnp.log(self.high - self.low))


class Categorical(Distribution):
    def __init__(self, logits=None, probs=None, name=None):
        if logits is None and probs is None:
            raise ValueError("need logits or probs")
        if logits is not None:
            self.logits = _arr(logits)
            if probs is not None:
                self.probs_ = _arr(probs)
            else:
                self.probs_ = jax.nn.softmax(self.logits, axis=-1)
        else:
            self.probs_ = _arr(probs) / jnp.sum(_arr(probs), -1,
                                                keepdims=True)
            self.logits = jnp.log(self.probs_ + 1e-38)
        super().__init__(np.shape(self.logits)[:-1])

    def sample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        out = jax.random.categorical(
            _random.split_key(), jnp.log(self.probs_ + 1e-38), shape=sh)
        return _t(out)

    def log_prob(self, value):
        v = _arr(value).astype(jnp.int32)
        logp = jnp.log(self.probs_ + 1e-38)
        if logp.ndim == 1:      # value is a vector of independent draws
            return _t(logp[v])
        return _t(jnp.take_along_axis(logp, v[..., None], axis=-1)[..., 0])

    def probs(self, value):
        v = _arr(value).astype(jnp.int32)
        if self.probs_.ndim == 1:
            return _t(self.probs_[v])
        return _t(jnp.take_along_axis(self.probs_, v[..., None],
                                      axis=-1)[..., 0])

    def entropy(self):
        p = self.probs_
        return _t(-jnp.sum(p * jnp.log(p + 1e-38), axis=-1))


class Bernoulli(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)
        super().__init__(np.shape(self.probs_))

    def sample(self, shape=()):
        sh = _shape(shape, self.probs_)
        return _t(jax.random.bernoulli(_random.split_key(), self.probs_,
                                       sh).astype(jnp.float32))

    def log_prob(self, value):
        v = _arr(value)
        p = self.probs_
        return _t(v * jnp.log(p + 1e-38) + (1 - v) * jnp.log1p(-p + 1e-38))

    def entropy(self):
        p = self.probs_
        return _t(-(p * jnp.log(p + 1e-38)
                    + (1 - p) * jnp.log1p(-p + 1e-38)))

    @property
    def mean(self):
        return _t(self.probs_)

    @property
    def variance(self):
        return _t(self.probs_ * (1 - self.probs_))


class Exponential(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(np.shape(self.rate))

    def sample(self, shape=()):
        sh = _shape(shape, self.rate)
        return _t(jax.random.exponential(_random.split_key(), sh)
                  / self.rate)

    def log_prob(self, value):
        v = _arr(value)
        return _t(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _t(1.0 - jnp.log(self.rate))

    @property
    def mean(self):
        return _t(1.0 / self.rate)


class Beta(Distribution):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _arr(alpha)
        self.beta = _arr(beta)
        super().__init__(np.shape(self.alpha))

    def sample(self, shape=()):
        sh = _shape(shape, self.alpha, self.beta)
        return _t(jax.random.beta(_random.split_key(), self.alpha,
                                  self.beta, sh))

    def log_prob(self, value):
        v = _arr(value)
        a, b = self.alpha, self.beta
        lbeta = (jax.scipy.special.gammaln(a)
                 + jax.scipy.special.gammaln(b)
                 - jax.scipy.special.gammaln(a + b))
        return _t((a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta)

    def entropy(self):
        from jax.scipy.special import gammaln, digamma
        a, b = self.alpha, self.beta
        lbeta = gammaln(a) + gammaln(b) - gammaln(a + b)
        return _t(lbeta - (a - 1) * digamma(a) - (b - 1) * digamma(b)
                  + (a + b - 2) * digamma(a + b))

    @property
    def mean(self):
        return _t(self.alpha / (self.alpha + self.beta))


class Dirichlet(Distribution):
    def __init__(self, concentration, name=None):
        self.concentration = _arr(concentration)
        super().__init__(np.shape(self.concentration)[:-1],
                         np.shape(self.concentration)[-1:])

    def sample(self, shape=()):
        sh = tuple(shape) + self._batch_shape
        return _t(jax.random.dirichlet(_random.split_key(),
                                       self.concentration, sh))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        c = self.concentration
        norm = jnp.sum(gammaln(c), -1) - gammaln(jnp.sum(c, -1))
        return _t(jnp.sum((c - 1) * jnp.log(v), -1) - norm)


class Gamma(Distribution):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _arr(concentration)
        self.rate = _arr(rate)
        super().__init__(np.shape(self.concentration))

    def sample(self, shape=()):
        sh = _shape(shape, self.concentration, self.rate)
        return _t(jax.random.gamma(_random.split_key(), self.concentration,
                                   sh) / self.rate)

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        a, r = self.concentration, self.rate
        return _t(a * jnp.log(r) + (a - 1) * jnp.log(v) - r * v
                  - gammaln(a))

    @property
    def mean(self):
        return _t(self.concentration / self.rate)


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(np.shape(self.loc))

    def sample(self, shape=()):
        sh = _shape(shape, self.loc, self.scale)
        return _t(self.loc + self.scale
                  * jax.random.laplace(_random.split_key(), sh))

    def log_prob(self, value):
        v = _arr(value)
        return _t(-jnp.abs(v - self.loc) / self.scale
                  - jnp.log(2 * self.scale))

    def entropy(self):
        return _t(1 + jnp.log(2 * self.scale)
                  + jnp.zeros(np.shape(self.loc)))


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = int(total_count)
        self.probs_ = _arr(probs)
        super().__init__(np.shape(self.probs_)[:-1],
                         np.shape(self.probs_)[-1:])

    def sample(self, shape=()):
        n = self.probs_.shape[-1]
        sh = tuple(shape) + self._batch_shape + (self.total_count,)
        draws = jax.random.categorical(
            _random.split_key(), jnp.log(self.probs_ + 1e-38), shape=sh)
        return _t(jnp.sum(jax.nn.one_hot(draws, n), axis=-2))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        logp = jnp.log(self.probs_ + 1e-38)
        return _t(gammaln(self.total_count + 1.0)
                  - jnp.sum(gammaln(v + 1.0), -1)
                  + jnp.sum(v * logp, -1))


class Poisson(Distribution):
    def __init__(self, rate, name=None):
        self.rate = _arr(rate)
        super().__init__(np.shape(self.rate))

    def sample(self, shape=()):
        sh = _shape(shape, self.rate)
        return _t(jax.random.poisson(_random.split_key(), self.rate,
                                     sh).astype(jnp.float32))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        return _t(v * jnp.log(self.rate) - self.rate - gammaln(v + 1.0))

    @property
    def mean(self):
        return _t(self.rate)


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs_ = _arr(probs)
        super().__init__(np.shape(self.probs_))

    def sample(self, shape=()):
        sh = _shape(shape, self.probs_)
        u = jax.random.uniform(_random.split_key(), sh)
        return _t(jnp.floor(jnp.log1p(-u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        v = _arr(value)
        return _t(v * jnp.log1p(-self.probs_) + jnp.log(self.probs_))


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(np.shape(self.loc))

    def sample(self, shape=()):
        sh = _shape(shape, self.loc, self.scale)
        return _t(self.loc + self.scale
                  * jax.random.cauchy(_random.split_key(), sh))

    def log_prob(self, value):
        v = _arr(value)
        z = (v - self.loc) / self.scale
        return _t(-jnp.log(math.pi * self.scale * (1 + jnp.square(z))))

    def entropy(self):
        return _t(jnp.log(4 * math.pi * self.scale))


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(np.shape(self.loc))

    def sample(self, shape=()):
        sh = _shape(shape, self.loc, self.scale)
        return _t(self.loc + self.scale
                  * jax.random.gumbel(_random.split_key(), sh))

    def log_prob(self, value):
        z = (_arr(value) - self.loc) / self.scale
        return _t(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    @property
    def mean(self):
        return _t(self.loc + self.scale * np.euler_gamma)


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _arr(df)
        self.loc = _arr(loc)
        self.scale = _arr(scale)
        super().__init__(jnp.broadcast_shapes(np.shape(self.df),
                                              np.shape(self.loc)))

    def sample(self, shape=()):
        sh = _shape(shape, self.df, self.loc, self.scale)
        return _t(self.loc + self.scale
                  * jax.random.t(_random.split_key(), self.df, sh))

    def log_prob(self, value):
        from jax.scipy.special import gammaln
        v = _arr(value)
        d = self.df
        z = (v - self.loc) / self.scale
        return _t(gammaln((d + 1) / 2) - gammaln(d / 2)
                  - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                  - (d + 1) / 2 * jnp.log1p(jnp.square(z) / d))


# ------------------------------------------------------------------- KL
def kl_divergence(p, q):
    """KL(p||q) for registered analytic pairs (reference
    python/paddle/distribution/kl.py)."""
    if isinstance(p, Normal) and isinstance(q, Normal):
        var_ratio = jnp.square(p.scale / q.scale)
        t1 = jnp.square((p.loc - q.loc) / q.scale)
        return _t(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
    if isinstance(p, Categorical) and isinstance(q, Categorical):
        return _t(jnp.sum(p.probs_ * (jnp.log(p.probs_ + 1e-38)
                                      - jnp.log(q.probs_ + 1e-38)), -1))
    if isinstance(p, Uniform) and isinstance(q, Uniform):
        return _t(jnp.log((q.high - q.low) / (p.high - p.low)))
    if isinstance(p, Bernoulli) and isinstance(q, Bernoulli):
        a, b = p.probs_, q.probs_
        return _t(a * (jnp.log(a + 1e-38) - jnp.log(b + 1e-38))
                  + (1 - a) * (jnp.log1p(-a + 1e-38)
                               - jnp.log1p(-b + 1e-38)))
    if isinstance(p, Exponential) and isinstance(q, Exponential):
        r = p.rate / q.rate
        return _t(jnp.log(r) + 1 / r - 1)
    raise NotImplementedError(
        f"kl_divergence not registered for "
        f"({type(p).__name__}, {type(q).__name__})")
